"""Streaming churn-epoch device stages: on-device column diff +
changed-rows compaction.

The streaming pipeline (`tpu_solver._stream_pipeline`, jit-cache
namespace "stream") fuses one churn epoch into a single dispatch: the
incremental bucketed relax (ops/relax.py + ops/incremental.py), the
best-route selection / LFA tail, and the column diff against the
PREVIOUS epoch's device-resident published planes — so the download per
epoch is a compacted changed-rows payload proportional to churn, not to
the prefix capacity. DeltaPath (arXiv 1808.06893) frames convergence as
one incrementally-maintained dataflow; these stages are the part of
that dataflow that decides what leaves the device.

`column_diff` / `compact_changed_rows` are traced under the pipeline
closure and are shared by the classic delta path (fixed budget, no ok
bit — the host re-derives route-ok while unpacking) and the streaming
path (bucketed budget from STREAM_BUDGETS, device ok bit riding the
payload so the host apply is unpack-free). One implementation, so the
two paths' changed sets are bit-identical by construction — the parity
property test pins device diff == fast_unicast_column_diff through
this sharing.

Streaming payload layout (int32 throughout, b = stream budget):

    [0]          count   total changed rows (may exceed b -> host
                         falls back to the device-compacted full pull)
    [1]          trips
    [2 : 2+b]    changed row indices (pad slots carry p_cap)
    ... b        metric
    ... b*wa     s3 words
    ... b*wd     nh words
    ... b        route-ok bit (STREAMING ONLY — absent on the classic
                 delta path, which recomputes ok host-side)
    ... 2b       lfa slot + metric        (lfa pipelines only)
    ... 2        unreachable, saturated   (sentinels enabled)
    ... 2        cone, fell_back          (incremental pipelines)
    [-1]         rounds
"""

from __future__ import annotations

import jax.numpy as jnp

# changed-rows download budgets for the streaming epoch payload. The
# solver tracks each vantage's recent changed-row count and picks the
# smallest bucket that held the last epoch (growing on overflow), so a
# quiet mesh downloads the 64-row floor and a flap storm settles into
# the bucket its churn rate needs. Quantized so budget churn can't
# thrash the "stream" jit-cache namespace (the budget is part of the
# executable's capacity signature).
STREAM_BUDGETS = (64, 256, 1024, 4096)


def stream_budget(n: int):
    """Smallest streaming budget bucket holding `n` changed rows, or
    None past the top bucket (the caller falls back to the full pull
    and the classic delta budget)."""
    for b in STREAM_BUDGETS:
        if n <= b:
            return b
    return None


def stream_payload_len(budget: int, wa: int, wd: int, lfa: bool,
                       sentinels: bool) -> int:
    """int32 element count of the streaming delta payload for a budget
    — the host-side mirror of the layout above. bytes_downloaded for a
    within-budget epoch is exactly 4x this, independent of p_cap."""
    n = 2 + budget * (3 + wa + wd)  # count, trips, idx/metric/ok, words
    if lfa:
        n += 2 * budget
    if sentinels:
        n += 2
    n += 2  # cone, fell_back — the streaming epoch is always incremental
    n += 1  # rounds
    return n


def column_diff(metric, s3w, nhw, lfa_slot, lfa_metric,
                prev_metric, prev_s3w, prev_nhw,
                prev_lfa_slot, prev_lfa_metric, lfa: bool):
    """bool [P]: rows whose published columns differ from the previous
    epoch's device-resident planes. The route-ok bit is a pure function
    of (metric, s3, nh) given a fixed matrix/root, so comparing the
    packed columns alone is complete — ok cannot flip on an unchanged
    row."""
    changed = (
        (metric != prev_metric)
        | jnp.any(s3w != prev_s3w, axis=1)
        | jnp.any(nhw != prev_nhw, axis=1)
    )
    if lfa:
        changed |= (lfa_slot != prev_lfa_slot) | (
            lfa_metric != prev_lfa_metric
        )
    return changed


def compact_changed_rows(changed, trips, metric, s3w, nhw, ok,
                         lfa_slot, lfa_metric, budget: int, p_cap: int,
                         lfa: bool):
    """(count, parts): head of the changed-rows payload — count, trips,
    then the changed rows' indices and packed columns gathered to the
    front (pad index slots carry p_cap; their gathered values are
    clipped reads the host masks off). `ok` is the device route-ok
    vector on the streaming path and None on the classic delta path,
    which keeps the classic payload layout byte-stable."""
    count = changed.sum().astype(jnp.int32)
    cidx = jnp.nonzero(changed, size=budget, fill_value=p_cap)[0]
    safe = jnp.clip(cidx, 0, p_cap - 1).astype(jnp.int32)
    parts = [
        count[None],
        trips[None].astype(jnp.int32),
        cidx.astype(jnp.int32),
        metric[safe],
        s3w[safe].ravel(),
        nhw[safe].ravel(),
    ]
    if ok is not None:
        parts.append(ok[safe].astype(jnp.int32))
    if lfa:
        parts += [lfa_slot[safe], lfa_metric[safe]]
    return count, parts
