"""Device-resident mirror of a LinkState graph.

Role in the architecture (SURVEY §7 step 3): the TPU solver does not walk
the host Link/adjacency objects — it operates on a padded array mirror
rebuilt (or delta-updated) from LinkState whenever Decision applies a
publication. This module owns that mirror.

Format: padded in-neighbor lists (ELL), not classic CSR index arrays.
The SSSP relaxation step

    dist'[v] = min(dist[v], min_k dist[in_nbr[v, k]] + in_w[v, k])

is then a dense gather + min-reduce over a static [N_cap, K_cap] array —
no scatter — which is the shape XLA tiles well onto the TPU VPU. (A
scatter-based segment-min over true CSR arrays is the GPU-idiomatic
formulation; on TPU scatters serialize, so we trade padding memory for
vectorization. Classic CSR arrays are also kept for out-edge enumeration
on the host side.)

Capacity classes: N_cap/K_cap/E_cap round up to the next power of two so
topology churn reuses compiled kernels instead of recompiling per node
count (SURVEY §7 hard part 3: dynamic topology in static shapes).

Mirrors the graph semantics of openr/decision/LinkState.h:185:
per-direction metrics, link up = neither side overloaded, node overload
(transit drain), and the root's out-edge table used for first-hop ("next
hop") extraction matching runSpf's accumulation (LinkState.cpp:885-901).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from openr_tpu.decision.link_state import Link, LinkState

INF32 = np.int32(2**30)  # effectively-infinite metric, addition-safe


def _next_pow2(n: int, floor: int = 8) -> int:
    c = floor
    while c < n:
        c *= 2
    return c


@dataclass
class EllGraph:
    """Host (numpy) padded-in-neighbor mirror; ship to device as-is."""

    n_nodes: int  # real node count (<= n_cap)
    n_cap: int
    k_cap: int  # padded max in-degree
    # [n_cap, k_cap]; in_nbr -1 = padding slot
    in_nbr: np.ndarray  # int32
    in_w: np.ndarray  # int32 (metric of edge in_nbr[v,k] -> v)
    in_up: np.ndarray  # bool  (link is up)
    node_overloaded: np.ndarray  # bool [n_cap]
    node_valid: np.ndarray  # bool [n_cap]
    # node index <-> name
    node_names: list  # idx -> name
    node_index: dict  # name -> idx
    # directed edge arrays (srcs/dsts/ws/ups aligned with edge_links) for
    # on-demand out-edge table extraction
    edge_src: np.ndarray  # int32 [E]
    edge_dst: np.ndarray  # int32 [E]
    edge_w: np.ndarray  # int32 [E]
    edge_up: np.ndarray  # bool [E]
    edge_links: list  # [E] Link refs (host materialization)
    # bumped only when the node name -> index mapping changes; derived
    # structures keyed on node indices (the prefix announcer matrix) stay
    # valid across metric/link churn that preserves the node set
    index_version: int = 0

    def out_table(self, root_idx: int, d_cap: Optional[int] = None):
        """Root's out-edge slot arrays for next-hop extraction:
        (nbr[d_cap], w[d_cap], up[d_cap], links list). Slot order is the
        deterministic sorted-Link order (edge arrays are built sorted)."""
        eids = np.flatnonzero(self.edge_src == root_idx)
        d_cap = d_cap or _next_pow2(max(len(eids), 1), floor=4)
        nbr = np.full(d_cap, -1, np.int32)
        w = np.full(d_cap, INF32, np.int32)
        up = np.zeros(d_cap, bool)
        eids = eids[:d_cap]
        n_out = len(eids)
        nbr[:n_out] = self.edge_dst[eids]
        w[:n_out] = self.edge_w[eids]
        up[:n_out] = self.edge_up[eids]
        links = [self.edge_links[e] for e in eids]
        return nbr, w, up, links


def build_ell(
    link_state: LinkState,
    n_cap: int = 0,
    k_cap: int = 0,
    prev: Optional[EllGraph] = None,
) -> EllGraph:
    """Mirror a LinkState into padded arrays (full rebuild path).

    The per-edge extraction is one Python pass over sorted links; the
    padded-array fill is fully vectorized (stable sort by destination +
    per-group slot offsets) — no per-edge numpy scalar writes. `prev`
    carries capacity floors and the index_version continuity."""
    names = sorted(link_state.get_adjacency_databases().keys())
    index = {n: i for i, n in enumerate(names)}
    n = len(names)
    if prev is not None:
        n_cap = max(n_cap, prev.n_cap)
        k_cap = max(k_cap, prev.k_cap)
    n_cap = max(n_cap, _next_pow2(n))

    # directed edge lists (u -> v with metric from u's side); one tight pass
    srcs: list[int] = []
    dsts: list[int] = []
    ws: list[int] = []
    ups: list[bool] = []
    edge_links: list[Link] = []
    s_app, d_app, w_app, u_app, l_app = (
        srcs.append, dsts.append, ws.append, ups.append, edge_links.append
    )
    for link in link_state.ordered_all_links():
        w1, w2, up = link.mirror_fields()
        i1, i2 = index[link.n1], index[link.n2]
        s_app(i1); d_app(i2); w_app(w1); u_app(up); l_app(link)
        s_app(i2); d_app(i1); w_app(w2); u_app(up); l_app(link)

    e = len(srcs)
    src_a = np.asarray(srcs, np.int32)
    dst_a = np.asarray(dsts, np.int32)
    w_a = np.asarray(ws, np.int32)
    up_a = np.asarray(ups, bool)

    if e:
        in_deg = np.bincount(dst_a, minlength=n_cap)
        k = int(in_deg.max())
    else:
        k = 0
    k_cap = max(k_cap, _next_pow2(max(k, 1), floor=4))

    in_nbr = np.full((n_cap, k_cap), -1, np.int32)
    in_w = np.full((n_cap, k_cap), INF32, np.int32)
    in_up = np.zeros((n_cap, k_cap), bool)
    if e:
        order = np.argsort(dst_a, kind="stable")
        sd = dst_a[order]
        # slot index within each destination group
        first = np.r_[0, np.flatnonzero(np.diff(sd)) + 1]
        counts = np.diff(np.r_[first, e])
        slots = np.arange(e) - np.repeat(first, counts)
        in_nbr[sd, slots] = src_a[order]
        in_w[sd, slots] = w_a[order]
        in_up[sd, slots] = up_a[order]

    node_overloaded = np.zeros(n_cap, bool)
    node_valid = np.zeros(n_cap, bool)
    node_valid[:n] = True
    overload = link_state.is_node_overloaded
    for i, name in enumerate(names):
        node_overloaded[i] = overload(name)

    index_version = 0
    if prev is not None:
        index_version = (
            prev.index_version
            if prev.node_names == names
            else prev.index_version + 1
        )

    return EllGraph(
        n_nodes=n,
        n_cap=n_cap,
        k_cap=k_cap,
        in_nbr=in_nbr,
        in_w=in_w,
        in_up=in_up,
        node_overloaded=node_overloaded,
        node_valid=node_valid,
        node_names=names,
        node_index=index,
        edge_src=src_a,
        edge_dst=dst_a,
        edge_w=w_a,
        edge_up=up_a,
        edge_links=edge_links,
        index_version=index_version,
    )


@dataclass
class PrefixMatrix:
    """Per-prefix announcer table for vectorized best-route selection.

    Row p mirrors PrefixState.entries_for(prefix_list[p]); columns are
    announcer slots (padded to a_cap). Preferences are compared
    lexicographically on device in the reference's order
    (path_preference desc, source_preference desc, advertised distance
    asc — LsdbUtil.cpp selectRoutes:842).
    """

    prefix_list: list  # row -> prefix string
    node_areas: list  # [p][a] -> (node, area) or None
    ann_node: np.ndarray  # int32 [P_cap, A_cap], -1 pad
    ann_valid: np.ndarray  # bool
    path_pref: np.ndarray  # int32
    source_pref: np.ndarray  # int32
    dist_adv: np.ndarray  # int32
    # host-side columns for vectorized route materialization
    min_nexthop: np.ndarray = None  # int32 [P_cap, A_cap], -1 = unset
    is_v4: np.ndarray = None  # bool [P_cap]
    # [p][a] -> PrefixEntry, aligned with node_areas: route entries are
    # materialized straight from these refs (no PrefixState lookups on
    # the hot host path)
    entry_refs: list = None
    # packed device-upload buffer memo (decision/tpu_solver._pack_matrix):
    # 5 of the 6 planes are pure functions of this matrix, so repacking
    # under overload churn rewrites only the flags segment in place
    # instead of re-concatenating all 6*P*A words
    _mbuf: np.ndarray = None


def build_prefix_matrix(
    prefix_state,
    node_index: dict,
    area: str,
    prefixes: Optional[list] = None,
    p_cap: int = 0,
    a_cap: int = 0,
) -> PrefixMatrix:
    """Pack one area's announcer entries into arrays. Announcers outside
    `node_index` (not in this area's graph) are dropped — same effect as
    the solver's reachability filter for unknown nodes.

    `prefixes` entries (and prefix_state keys) are canonical strings, so
    rows read the state map directly; the common single-announcer row
    skips the announcer sort."""
    state_map = prefix_state.prefixes()
    all_prefixes = prefixes if prefixes is not None else sorted(state_map)
    rows = []
    a_max = 1
    for pfx in all_prefixes:
        entries = state_map.get(pfx) or {}
        if len(entries) == 1:
            na, e = next(iter(entries.items()))
            anns = (
                [(na, e)] if na[1] == area and na[0] in node_index else []
            )
        else:
            anns = [
                (na, e)
                for na, e in sorted(entries.items())
                if na[1] == area and na[0] in node_index
            ]
            if len(anns) > a_max:
                a_max = len(anns)
        rows.append((pfx, anns))
    p = len(rows)
    p_cap = max(p_cap, _next_pow2(max(p, 1)))
    a_cap = max(a_cap, _next_pow2(max(a_max, 1), floor=2))

    ann_node = np.full((p_cap, a_cap), -1, np.int32)
    ann_valid = np.zeros((p_cap, a_cap), bool)
    path_pref = np.full((p_cap, a_cap), np.int32(-(2**31)), np.int32)
    source_pref = np.full((p_cap, a_cap), np.int32(-(2**31)), np.int32)
    dist_adv = np.full((p_cap, a_cap), INF32, np.int32)
    min_nexthop = np.full((p_cap, a_cap), -1, np.int32)
    is_v4 = np.zeros(p_cap, bool)
    prefix_list = []
    node_areas = []
    entry_refs = []
    # cell values buffered as tuples, scattered into the padded arrays
    # in one shot (per-cell numpy scalar stores are ~10x slower at the
    # 100k-prefix scale)
    cells: list[tuple] = []
    cell_append = cells.append
    pl_append = prefix_list.append
    na_append = node_areas.append
    er_append = entry_refs.append
    for pi, (pfx, anns) in enumerate(rows):
        pl_append(pfx)
        if len(anns) == 1:
            na, entry = anns[0]
            m = entry.metrics
            cell_append((
                pi, 0, node_index[na[0]], m.path_preference,
                m.source_preference, m.distance,
                -1 if entry.min_nexthop is None else entry.min_nexthop,
            ))
            na_append([na])
            er_append([entry])
            continue
        row_nas = []
        row_entries = []
        for ai, (na, entry) in enumerate(anns[:a_cap]):
            m = entry.metrics
            cell_append((
                pi, ai, node_index[na[0]], m.path_preference,
                m.source_preference, m.distance,
                -1 if entry.min_nexthop is None else entry.min_nexthop,
            ))
            row_nas.append(na)
            row_entries.append(entry)
        na_append(row_nas)
        er_append(row_entries)
    if p:
        is_v4[:p] = np.fromiter(
            (":" not in pfx for pfx in prefix_list), bool, p
        )
    if cells:
        c_pi, c_ai, c_node, c_pp, c_sp, c_da, c_mn = zip(*cells)
        pi_a = np.asarray(c_pi, np.int64)
        ai_a = np.asarray(c_ai, np.int64)
        ann_node[pi_a, ai_a] = c_node
        ann_valid[pi_a, ai_a] = True
        path_pref[pi_a, ai_a] = c_pp
        source_pref[pi_a, ai_a] = c_sp
        dist_adv[pi_a, ai_a] = np.minimum(
            np.asarray(c_da, np.int64), int(INF32)
        )
        min_nexthop[pi_a, ai_a] = c_mn
    return PrefixMatrix(
        prefix_list=prefix_list,
        node_areas=node_areas,
        ann_node=ann_node,
        ann_valid=ann_valid,
        path_pref=path_pref,
        source_pref=source_pref,
        dist_adv=dist_adv,
        min_nexthop=min_nexthop,
        is_v4=is_v4,
        entry_refs=entry_refs,
    )
