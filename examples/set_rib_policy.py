"""Push a weight-steering RibPolicy into a running node's Decision
(role of the reference's examples/SetRibPolicyExample.cpp).

    python examples/set_rib_policy.py --port <ctrl-port> \
        --prefix 10.0.0.2/32 --neighbor node-b --weight 9
"""

import argparse
import asyncio

from openr_tpu.runtime.rpc import RpcClient


async def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--prefix", required=True)
    ap.add_argument("--neighbor", required=True)
    ap.add_argument("--weight", type=int, default=2)
    ap.add_argument("--ttl-secs", type=int, default=300)
    args = ap.parse_args()

    policy = {
        "statements": [
            {
                "name": "steer",
                "prefixes": [args.prefix],
                "action": {
                    "default_weight": 1,
                    "neighbor_to_weight": {args.neighbor: args.weight},
                },
            }
        ],
        "ttl_secs": args.ttl_secs,
    }
    client = RpcClient("127.0.0.1", args.port, name="set-rib-policy")
    try:
        await client.request(
            "ctrl.decision.set_rib_policy", {"policy": policy}
        )
        print("policy installed:", await client.request(
            "ctrl.decision.get_rib_policy"
        ))
    finally:
        await client.close()


if __name__ == "__main__":
    asyncio.run(main())
