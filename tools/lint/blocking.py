"""Blocking-in-fiber checker (`blocking-call`).

Every actor fiber shares ONE asyncio event loop (runtime/actor.py), so
a synchronous block inside any `async def` stalls every module at once
— the reference's per-module EventBase threads would only stall one.
Flagged inside async function bodies (nested synchronous `def`s are
excluded — they run wherever they're called, typically an executor):

  - `time.sleep(...)` — use `asyncio.sleep`
  - `<fut>.result()` / `<fut>.exception()` on concurrent futures —
    await it, or drain it in an executor
  - synchronous socket I/O (`socket.socket(...)` construction plus
    `.recv/.accept/.connect/...` calls) — use loop transports/executors
  - a direct `collect_route_db(...)` call — the ONE blocking host sync
    of a solve; the dispatch-collect split exists precisely so this
    runs via `run_in_executor` (decision.py's `_solve_full_async`)

Handing the bound method itself to an executor
(`run_in_executor(None, self.solver.collect_route_db, build)`) is not
a call and is not flagged.
"""

from __future__ import annotations

import ast

from tools.lint.core import Finding, Project

CODE = "blocking-call"

# NOTE: "sendto" is deliberately absent — asyncio's DatagramTransport
# exposes a non-blocking sendto(), so the name alone can't distinguish
# the sync-socket case (io_provider.py's transports would all flag)
_SOCKET_IO = {
    "recv", "recvfrom", "recv_into", "recvmsg", "sendall",
    "accept", "connect", "makefile",
}


def _call_repr(fn: ast.AST) -> str:
    try:
        return ast.unparse(fn)
    except Exception:  # pragma: no cover - unparse of exotic nodes
        return "<call>"


class _AsyncBodyVisitor(ast.NodeVisitor):
    """Collects blocking calls lexically inside async defs, skipping
    nested synchronous defs (they execute off-loop by construction)."""

    def __init__(self, sf, findings: list[Finding]):
        self.sf = sf
        self.findings = findings
        self.async_depth = 0
        # id()s of Call nodes directly under an `await` — an awaited
        # coroutine method (await self.connect(), await self.io.recv())
        # is the non-blocking pattern, not a sync call
        self._awaited: set[int] = set()

    def visit_Await(self, node: ast.Await) -> None:
        if isinstance(node.value, ast.Call):
            self._awaited.add(id(node.value))
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self.async_depth += 1
        self.generic_visit(node)
        self.async_depth -= 1

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        saved = self.async_depth
        self.async_depth = 0
        self.generic_visit(node)
        self.async_depth = saved

    visit_Lambda = visit_FunctionDef  # type: ignore[assignment]

    def _flag(self, node: ast.Call, detail: str, why: str) -> None:
        self.findings.append(Finding(
            self.sf.rel, node.lineno, CODE,
            self.sf.scope_at(node.lineno), detail,
            f"blocking call `{_call_repr(node.func)}` inside an async "
            f"fiber — {why}",
        ))

    def visit_Call(self, node: ast.Call) -> None:
        if self.async_depth > 0 and id(node) not in self._awaited:
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "time"
                and fn.attr == "sleep"
            ):
                self._flag(node, "time.sleep", "use asyncio.sleep")
            elif isinstance(fn, ast.Attribute) and fn.attr in (
                "result", "exception"
            ) and not node.args and not node.keywords:
                self._flag(
                    node, f"{fn.attr}()",
                    "await the future or drain it in an executor",
                )
            elif isinstance(fn, ast.Attribute) and fn.attr in _SOCKET_IO:
                self._flag(
                    node, fn.attr,
                    "sync socket I/O stalls every actor — use loop "
                    "transports or an executor",
                )
            elif (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "socket"
                and fn.attr == "socket"
            ):
                self._flag(
                    node, "socket.socket",
                    "sync socket construction in a fiber — use loop "
                    "transports",
                )
            elif (
                isinstance(fn, ast.Attribute)
                and fn.attr == "collect_route_db"
            ):
                self._flag(
                    node, "collect_route_db",
                    "the one blocking host sync of a solve must run "
                    "via run_in_executor (dispatch-collect split)",
                )
        self.generic_visit(node)


def run(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        _AsyncBodyVisitor(sf, findings).visit(sf.tree)
    return findings
