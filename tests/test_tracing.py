"""Convergence tracing fabric tests (runtime/tracing.py).

Three layers: Tracer unit semantics (span trees, disabled fast path,
eviction), context propagation through ReplicateQueue and through a
real multi-node in-process daemon, and the export surfaces (Chrome
trace-event schema, percentile math vs numpy).
"""

import asyncio
import gc
import json
import random
import time

from openr_tpu.messaging import ReplicateQueue
from openr_tpu.runtime.counters import CounterRegistry, _percentile
from openr_tpu.runtime.tracing import Tracer, tracer
from tests.conftest import run_async


class _Item:
    """Weakref-able stand-in for a queue payload."""


class TestTracerUnit:
    def test_span_tree_closes_ok(self):
        t = Tracer()
        ctx = t.start_trace("convergence", node="n0", origin="local")
        assert ctx is not None
        with t.span(ctx, "decision.spf", node="n0") as sp:
            sp.set(full=True)
        t.record_span(ctx, "tpu.exec", 1.0, 1.5, area="0")
        t.end_trace(ctx, status="ok", routes=3)
        (tr,) = t.get_traces()
        assert tr["status"] == "ok"
        assert tr["duration_ms"] >= 0
        names = [s["name"] for s in tr["spans"]]
        assert names == ["convergence", "decision.spf", "tpu.exec"]
        root = tr["spans"][0]
        assert root["attributes"]["routes"] == 3
        # children default-parent to the root span
        for s in tr["spans"][1:]:
            assert s["parent_id"] == root["span_id"]
        spf = tr["spans"][1]
        assert spf["attributes"]["full"] is True
        assert spf["duration_ms"] is not None and spf["duration_ms"] >= 0
        exec_sp = tr["spans"][2]
        assert abs(exec_sp["duration_ms"] - 500.0) < 1e-6

    def test_disabled_is_null_path(self):
        t = Tracer()
        t.configure(enabled=False)
        assert t.start_trace("convergence") is None
        assert t.attach(_Item(), None) is False
        # every entry point must take the None fast path silently
        with t.span(None, "x") as sp:
            assert sp is None
        t.end_span(None)
        t.end_trace(None)
        assert t.get_traces() == []
        t.configure(enabled=True)
        assert t.start_trace("convergence") is not None

    def test_non_ok_statuses_do_not_count_convergence(self):
        t = Tracer()
        for status in ("coalesced", "no_change", "ignored"):
            ctx = t.start_trace("convergence")
            t.end_trace(ctx, status=status)
        assert [tr["status"] for tr in t.get_traces()] == [
            "coalesced", "no_change", "ignored"
        ]
        assert t.convergence_summary()["count"] == 0

    def test_active_trace_eviction_valve(self):
        from openr_tpu.runtime import tracing

        t = Tracer()
        for _ in range(tracing.MAX_ACTIVE_TRACES + 1):
            t.start_trace("convergence")
        evicted = [
            tr for tr in t.get_traces(limit=1000) if tr["status"] == "evicted"
        ]
        assert len(evicted) == 1
        # the oldest trace (trace_id 1) is the one sacrificed
        assert evicted[0]["trace_id"] == 1

    def test_convergence_summary_percentiles(self):
        t = Tracer()
        ctxs = [t.start_trace("convergence") for _ in range(40)]
        for ctx in ctxs:
            t.end_trace(ctx, status="ok")
        summary = t.convergence_summary()
        assert summary["count"] == 40
        assert summary["p50_ms"] <= summary["p95_ms"] <= summary["p99_ms"]
        assert summary["p99_ms"] <= summary["max_ms"]


class TestQueuePropagation:
    @run_async
    async def test_context_rides_replicate_queue(self):
        q = ReplicateQueue("trace-test")
        reader = q.get_reader("r0")
        ctx = tracer.start_trace("convergence", node="n0")
        item = _Item()
        q.push(item, trace=ctx)
        got = await reader.get()
        assert got is item
        assert tracer.context_of(got) is ctx
        tracer.end_trace(ctx, status="ok")
        q.close()

    @run_async
    async def test_push_without_trace_leaves_no_entry(self):
        q = ReplicateQueue("trace-test-2")
        reader = q.get_reader("r0")
        item = _Item()
        q.push(item)
        got = await reader.get()
        assert tracer.context_of(got) is None
        q.close()

    @run_async
    async def test_side_table_scrubbed_on_gc(self):
        q = ReplicateQueue("trace-test-3")
        reader = q.get_reader("r0")
        ctx = tracer.start_trace("convergence", node="n0")
        item = _Item()
        key = id(item)
        q.push(item, trace=ctx)
        got = await reader.get()
        tracer.end_trace(ctx, status="ok")
        del item, got
        gc.collect()
        assert key not in tracer._ctx_by_id
        q.close()

    def test_side_table_evicts_orphans_first_at_cap(self):
        """ISSUE 11 satellite: a wedged consumer strands contexts of
        already-closed traces; at the cap those orphans go first and the
        still-active trace's context survives."""
        from openr_tpu.runtime import tracing
        from openr_tpu.runtime.counters import counters

        t = Tracer()
        ev0 = counters.get_counter("tracing.contexts_evicted") or 0
        dead_ctx = t.start_trace("convergence", node="n0")
        t.end_trace(dead_ctx, status="ok")
        live_ctx = t.start_trace("convergence", node="n0")
        # strong refs: the finalizer path must not be what empties the
        # table in this test
        stranded = [_Item() for _ in range(tracing.MAX_TRACE_CONTEXTS)]
        for it in stranded:
            assert t.attach(it, dead_ctx)
        live_item = _Item()
        assert t.attach(live_item, live_ctx)
        # over-cap attach swept the orphans, kept the live context
        assert t.active_context_count() <= tracing.MAX_TRACE_CONTEXTS
        assert t.context_of(live_item) is live_ctx
        assert t.context_of(stranded[0]) is None
        ev1 = counters.get_counter("tracing.contexts_evicted") or 0
        assert ev1 - ev0 >= tracing.MAX_TRACE_CONTEXTS
        t.end_trace(live_ctx, status="ok")

    def test_side_table_evicts_oldest_when_all_live(self):
        from openr_tpu.runtime import tracing

        t = Tracer()
        live_ctx = t.start_trace("convergence", node="n0")
        items = [
            _Item() for _ in range(tracing.MAX_TRACE_CONTEXTS + 5)
        ]
        for it in items:
            assert t.attach(it, live_ctx)
        assert t.active_context_count() == tracing.MAX_TRACE_CONTEXTS
        # oldest-first: the first attaches were sacrificed, newest kept
        assert t.context_of(items[0]) is None
        assert t.context_of(items[-1]) is live_ctx
        t.end_trace(live_ctx, status="ok")


class TestQuantileMath:
    def test_percentile_matches_numpy(self):
        import numpy as np

        rng = random.Random(42)
        vals = [rng.uniform(0.1, 500.0) for _ in range(257)]
        ordered = sorted(vals)
        for q in (50.0, 95.0, 99.0, 0.0, 100.0, 37.5):
            ours = _percentile(ordered, q)
            theirs = float(np.percentile(vals, q))
            assert abs(ours - theirs) < 1e-9, (q, ours, theirs)

    def test_stat_windows_report_percentiles(self):
        import numpy as np

        reg = CounterRegistry()
        rng = random.Random(7)
        vals = [rng.uniform(1.0, 100.0) for _ in range(100)]
        for v in vals:
            reg.add_stat_value("lat_ms", v)
        win = reg.get_statistics("lat_ms")["lat_ms"]["3600"]
        assert win["count"] == 100
        for q, key in ((50.0, "p50"), (95.0, "p95"), (99.0, "p99")):
            assert abs(win[key] - float(np.percentile(vals, q))) < 1e-9
        assert win["max"] == max(vals)

    def test_empty_stat_window_is_zeroed(self):
        reg = CounterRegistry()
        reg.add_stat_value("once", 5.0)
        win = reg.get_statistics("once")["once"]["3600"]
        assert win["p50"] == win["p95"] == win["p99"] == 5.0


class TestChromeExport:
    def test_export_schema(self):
        t = Tracer()
        ctx = t.start_trace("convergence", node="n0", origin="local")
        with t.span(ctx, "decision.spf"):
            pass
        t.record_span(ctx, "tpu.exec", 1.0, 1.25, area="0")
        t.end_trace(ctx, status="ok")
        doc = json.loads(t.export_chrome_json())
        assert doc["displayTimeUnit"] == "ms"
        events = doc["traceEvents"]
        metas = [e for e in events if e["ph"] == "M"]
        xs = [e for e in events if e["ph"] == "X"]
        # one process lane per node (named after it) + thread names
        assert metas and all(
            e["name"] in ("process_name", "thread_name") for e in metas
        )
        procs = [e for e in metas if e["name"] == "process_name"]
        assert [e["args"]["name"] for e in procs] == ["n0"]
        assert len(xs) == 3  # root + 2 children
        for e in xs:
            assert isinstance(e["ts"], float) and e["ts"] > 0
            assert isinstance(e["dur"], float) and e["dur"] >= 0
            assert e["pid"] and e["tid"]
            assert e["cat"] == "convergence"
            assert "trace_id" in e["args"] and "span_id" in e["args"]
        # only closed spans export: an active trace contributes nothing
        ctx2 = t.start_trace("convergence")
        doc2 = t.export_chrome()
        assert len([e for e in doc2["traceEvents"] if e["ph"] == "X"]) == 3
        t.end_trace(ctx2, status="ok")

    def test_export_filters_by_trace_id(self):
        t = Tracer()
        c1 = t.start_trace("convergence")
        t.end_trace(c1, status="ok")
        c2 = t.start_trace("convergence")
        t.end_trace(c2, status="ok")
        doc = t.export_chrome(trace_id=c1.trace_id)
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert len(xs) == 1 and xs[0]["args"]["trace_id"] == c1.trace_id


class TestTwoNodeTracePropagation:
    """ISSUE acceptance: one topology event entering node-a's KvStore
    must carry a single trace_id kvstore -> decision -> fib on the node
    whose routes change — across ReplicateQueues inside a real two-node
    in-process daemon."""

    @run_async
    async def test_one_trace_spans_pipeline(self):
        from openr_tpu.kvstore.wrapper import wait_until
        from openr_tpu.runtime.openr_wrapper import OpenrWrapper
        from openr_tpu.spark import MockIoMesh

        tracer.clear()
        mesh = MockIoMesh()
        kv_ports: dict[str, int] = {}
        a = OpenrWrapper("node-a", mesh.provider("node-a"), kv_ports)
        b = OpenrWrapper("node-b", mesh.provider("node-b"), kv_ports)
        mesh.connect("node-a", "if-ab", "node-b", "if-ba")
        await a.start("if-ab")
        await b.start("if-ba")
        try:
            b.advertise_prefix("10.7.0.0/24")
            await wait_until(
                lambda: "10.7.0.0/24" in a.fib_routes, timeout_s=20
            )

            def node_a_ok_traces():
                return [
                    tr for tr in tracer.get_traces(limit=200)
                    if tr["status"] == "ok"
                    and tr["spans"][0]["attributes"].get("node") == "node-a"
                ]

            # the FIB ack (end_trace) can land just after the route shows
            # up in fib_routes — wait for the closure too
            await wait_until(lambda: len(node_a_ok_traces()) > 0,
                             timeout_s=10)
            tr = node_a_ok_traces()[-1]
            names = {s["name"] for s in tr["spans"]}
            assert "convergence" in names
            assert "kvstore.publication" in names
            assert "decision.spf" in names
            assert "fib.diff" in names
            assert "platform.program" in names
            # every span belongs to the one trace
            ids = {s["trace_id"] for s in tr["spans"]}
            assert ids == {tr["trace_id"]}
        finally:
            for w in (a, b):
                await w.stop()


class TestSystemConvergenceTrace:
    """ISSUE acceptance (system): 3-node topology, one link-metric
    change -> a single closed trace with >= 5 pipeline stages on the
    rerouting node; its Chrome JSON parses; monitor.statistics (ctrl)
    reports a non-zero decision.spf_ms p99."""

    @run_async
    async def test_link_metric_change_single_trace(self):
        from openr_tpu.kvstore.wrapper import wait_until
        from openr_tpu.runtime.openr_wrapper import OpenrWrapper
        from openr_tpu.runtime.rpc import RpcClient
        from openr_tpu.spark import MockIoMesh

        mesh = MockIoMesh()
        kv_ports: dict[str, int] = {}
        names = ["node-0", "node-1", "node-2"]
        nodes = {
            n: OpenrWrapper(
                n, mesh.provider(n), kv_ports,
                enable_ctrl=(n == "node-0"),
            )
            for n in names
        }
        links = [
            ("node-0", "if-01", "node-1", "if-10"),
            ("node-1", "if-12", "node-2", "if-21"),
            ("node-2", "if-20", "node-0", "if-02"),
        ]
        for x, ifx, y, ify in links:
            mesh.connect(x, ifx, y, ify)
        ifaces = {n: [] for n in names}
        for x, ifx, y, ify in links:
            ifaces[x].append(ifx)
            ifaces[y].append(ify)
        for n, w in nodes.items():
            await w.start(*ifaces[n])
        try:
            for i, n in enumerate(names):
                nodes[n].advertise_prefix(f"10.0.0.{i + 1}/32")
            await wait_until(
                lambda: all(
                    f"10.0.0.{j + 1}/32" in nodes[n].fib_routes
                    for n in names
                    for j in range(3)
                    if names[j] != n
                ),
                timeout_s=20,
            )
            # direct next hop before the change
            entry = nodes["node-0"].fib_routes["10.0.0.2/32"]
            assert {nh.neighbor_node_name for nh in entry.nexthops} == {
                "node-1"
            }

            # quiesce, then ONE topology event: node-0's link to node-1
            # becomes expensive, so node-0 must reroute via node-2
            tracer.clear()
            await nodes["node-0"].link_monitor.set_link_metric("if-01", 100)

            def rerouted():
                e = nodes["node-0"].fib_routes.get("10.0.0.2/32")
                return e is not None and {
                    nh.neighbor_node_name for nh in e.nexthops
                } == {"node-2"}

            await wait_until(rerouted, timeout_s=20)

            def node0_ok_traces():
                return [
                    tr for tr in tracer.get_traces(limit=200)
                    if tr["status"] == "ok"
                    and tr["spans"][0]["attributes"].get("node") == "node-0"
                ]

            await wait_until(lambda: len(node0_ok_traces()) > 0,
                             timeout_s=10)
            oks = node0_ok_traces()
            # the one metric change produces exactly one convergence
            # event on node-0 (debounce coalesces, echo floods are no-ops)
            assert len(oks) == 1, [t["trace_id"] for t in oks]
            tr = oks[0]
            assert tr["num_spans"] >= 5, [s["name"] for s in tr["spans"]]
            assert tr["duration_ms"] > 0

            # Chrome export of that trace parses and carries its spans
            doc = json.loads(
                tracer.export_chrome_json(trace_id=tr["trace_id"])
            )
            xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
            assert len(xs) == tr["num_spans"]

            # ctrl surface: monitor.statistics has a non-zero spf p99,
            # and the convergence endpoint reflects the closed trace
            client = RpcClient("127.0.0.1", nodes["node-0"].ctrl.port)
            try:
                stats = await client.request(
                    "monitor.statistics", {"prefix": "decision.spf_ms"}
                )
                assert stats["decision.spf_ms"]["3600"]["p99"] > 0
                conv = await client.request("ctrl.decision.convergence")
                assert conv["summary"]["count"] >= 1
                assert conv["summary"]["p99_ms"] > 0
                chrome = await client.request(
                    "monitor.traces.export_chrome",
                    {"trace_id": tr["trace_id"]},
                )
                assert chrome["traceEvents"]
                listed = await client.request(
                    "monitor.traces", {"trace_id": tr["trace_id"]}
                )
                assert listed and listed[0]["trace_id"] == tr["trace_id"]
            finally:
                await client.close()
        finally:
            for w in nodes.values():
                await w.stop()


class TestFleetConvergenceStitching:
    """ISSUE 11 acceptance (system): a link-metric change at node A
    produces a STITCHED fleet trace — every node's convergence trace
    carries node A's origin stamp, each Fib ack reports
    fleet_convergence_ms back through the monitor:conv-ack: fabric, the
    ctrl fleet view aggregates origin→last-FIB-ack across all three
    nodes with the straggler attributed, and the Chrome export renders
    one process lane per node."""

    @run_async
    async def test_fleet_trace_stitching_three_nodes(self):
        from openr_tpu.kvstore.wrapper import wait_until
        from openr_tpu.runtime.openr_wrapper import OpenrWrapper
        from openr_tpu.runtime.rpc import RpcClient
        from openr_tpu.spark import MockIoMesh

        mesh = MockIoMesh()
        kv_ports: dict[str, int] = {}
        names = ["node-0", "node-1", "node-2"]
        nodes = {
            n: OpenrWrapper(
                n, mesh.provider(n), kv_ports,
                enable_ctrl=(n == "node-0"),
            )
            for n in names
        }
        links = [
            ("node-0", "if-01", "node-1", "if-10"),
            ("node-1", "if-12", "node-2", "if-21"),
            ("node-2", "if-20", "node-0", "if-02"),
        ]
        for x, ifx, y, ify in links:
            mesh.connect(x, ifx, y, ify)
        ifaces = {n: [] for n in names}
        for x, ifx, y, ify in links:
            ifaces[x].append(ifx)
            ifaces[y].append(ify)
        for n, w in nodes.items():
            await w.start(*ifaces[n])
        try:
            for i, n in enumerate(names):
                nodes[n].advertise_prefix(f"10.0.0.{i + 1}/32")
            await wait_until(
                lambda: all(
                    f"10.0.0.{j + 1}/32" in nodes[n].fib_routes
                    for n in names
                    for j in range(3)
                    if names[j] != n
                ),
                timeout_s=20,
            )
            # quiesce, then ONE topology event at node-0
            tracer.clear()
            t_before_ms = time.time() * 1000.0
            await nodes["node-0"].link_monitor.set_link_metric("if-01", 100)

            def rerouted():
                e = nodes["node-0"].fib_routes.get("10.0.0.2/32")
                return e is not None and {
                    nh.neighbor_node_name for nh in e.nexthops
                } == {"node-2"}

            await wait_until(rerouted, timeout_s=20)

            # every node's convergence trace carries node-0's origin
            # stamp on its root span — the stitched fleet trace. The
            # origin node reroutes ("ok"); the receivers correctly
            # conclude "no_change" (their directed out-edges are
            # untouched) but are STILL stitched to the same event.
            def stamped_nodes():
                out = {}
                for tr in tracer.get_traces(limit=200):
                    if tr["status"] not in ("ok", "no_change"):
                        continue
                    attrs = tr["spans"][0]["attributes"]
                    if attrs.get("origin_node") == "node-0":
                        out.setdefault(attrs.get("node"), attrs)
                return out

            await wait_until(
                lambda: set(stamped_nodes()) == set(names),
                timeout_s=20,
            )
            stamped = stamped_nodes()
            event_ids = {a["origin_event_id"] for a in stamped.values()}
            assert len(event_ids) == 1, stamped  # ONE origin event
            (event_id,) = event_ids
            assert event_id.startswith("node-0:"), event_id
            for attrs in stamped.values():
                assert attrs["origin_ts_ms"] >= t_before_ms - 60_000

            # second origin event: a NEW prefix from node-0 forces BOTH
            # receivers to program a route, so its fleet row carries two
            # acks and a meaningful straggler
            t_prefix_ms = time.time() * 1000.0
            nodes["node-0"].advertise_prefix("10.0.99.1/32")
            await wait_until(
                lambda: all(
                    "10.0.99.1/32" in nodes[n].fib_routes
                    for n in ("node-1", "node-2")
                ),
                timeout_s=20,
            )

            # fleet view from node-0's ctrl port: the event aggregated
            # across all three conv-ack rings, straggler attributed
            client = RpcClient("127.0.0.1", nodes["node-0"].ctrl.port)
            try:
                def prefix_row(conv):
                    # pick the first post-advertise node-0 event both
                    # receivers acked (rows carry the origin ts)
                    return next(
                        (
                            r
                            for r in conv["fleet"]["events"]
                            if r["origin"] == "node-0"
                            and r["ts_ms"] >= t_prefix_ms - 1.0
                            and {"node-1", "node-2"} <= set(r["acks"])
                        ),
                        None,
                    )

                conv = None
                row = None
                for _ in range(80):
                    conv = await client.request(
                        "ctrl.decision.convergence", {"fleet": True}
                    )
                    row = prefix_row(conv)
                    if row is not None:
                        break
                    await asyncio.sleep(0.25)
                fleet = conv["fleet"]
                assert row is not None, fleet["events"]
                assert row["nodes_acked"] >= 2, row
                # origin→last-FIB-ack: the fleet number IS the slowest
                # node's ack, and the straggler is that node
                assert row["fleet_ms"] == max(row["acks"].values()), row
                assert row["straggler"] == max(
                    row["acks"], key=row["acks"].get
                ), row
                assert row["fleet_ms"] >= 0
                # the metric-change event is in the fleet view too, with
                # the origin node's own reprogram ack
                mrow = next(
                    (
                        r
                        for r in fleet["events"]
                        if r["event"] == event_id
                    ),
                    None,
                )
                assert mrow is not None, fleet["events"]
                assert "node-0" in mrow["acks"], mrow
                # all three nodes contribute conv-ack rings
                assert set(fleet["nodes_reporting"]) == set(names), fleet
                assert fleet["fleet_ms"]["count"] >= 1
                assert (
                    fleet["fleet_ms"]["max"] >= fleet["fleet_ms"]["p50"]
                )
            finally:
                await client.close()

            # Chrome export: one process lane per NODE, named after it
            doc = json.loads(tracer.export_chrome_json(limit=200))
            lanes = {
                e["args"]["name"]: e["pid"]
                for e in doc["traceEvents"]
                if e["ph"] == "M" and e["name"] == "process_name"
            }
            assert set(names) <= set(lanes), lanes
            assert len({lanes[n] for n in names}) == 3, lanes
            # every X event rides one of the node lanes
            pids = set(lanes.values())
            assert all(
                e["pid"] in pids
                for e in doc["traceEvents"]
                if e["ph"] == "X"
            )
        finally:
            for w in nodes.values():
                await w.stop()
