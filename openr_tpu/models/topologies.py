"""Topology generators shared by tests and benchmarks.

Role of the reference's openr/decision/tests/RoutingBenchmarkUtils.{h,cpp}:
grid (createGrid:308), fat-tree fabric (createFabric:361 with
kNumOfSswsPerPlane=36, kNumOfRswsPerPod=48 markers, :93-99), plus ring and
full-mesh used by the system tests (openr/tests/OpenrSystemTest.cpp).

Each generator returns (adj_dbs, prefix_dbs):
  adj_dbs:    list[AdjacencyDatabase] — one per node, bidirectional pairs
  prefix_dbs: list[PrefixDatabase]    — one per (node, prefix) key

These feed LinkState/PrefixState directly, the Decision actor via synthetic
KvStore publications, and the CSR mirror for the TPU solver — one source of
truth for every layer's test input.
"""

from __future__ import annotations

import random

from openr_tpu.types import (
    Adjacency,
    AdjacencyDatabase,
    PrefixDatabase,
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
    PrefixType,
)


def build_states(adj_dbs, prefix_dbs):
    """Materialize (area -> LinkState, PrefixState) from generator output —
    the direct-injection path used by solver tests and bench.py (the Decision
    actor builds the same states from KvStore publications)."""
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState

    link_states: dict[str, LinkState] = {}
    for db in adj_dbs:
        link_states.setdefault(db.area, LinkState(db.area)).update_adjacency_database(db)
    prefix_state = PrefixState()
    for db in prefix_dbs:
        prefix_state.update_prefix_database(db)
    return link_states, prefix_state


def _adj(me: str, other: str, metric: int = 1, weight: int = 1) -> Adjacency:
    return Adjacency(
        other_node_name=other,
        if_name=f"if-{me}-{other}",
        other_if_name=f"if-{other}-{me}",
        metric=metric,
        weight=weight,
    )


def _loopback_prefix(node_idx: int, v4: bool = False) -> str:
    if v4:
        return f"10.{(node_idx >> 16) & 0xFF}.{(node_idx >> 8) & 0xFF}.{node_idx & 0xFF}/32"
    hi, lo = node_idx >> 16, node_idx & 0xFFFF
    return f"fd00::{hi:x}:{lo:x}/128" if hi else f"fd00::{lo:x}/128"


def _mk_dbs(
    nodes: dict[str, list[Adjacency]],
    area: str,
    forwarding_algorithm: PrefixForwardingAlgorithm,
    node_labels: bool,
    prefixes_per_node: int = 1,
    ksp2_every: int = 0,
) -> tuple[list[AdjacencyDatabase], list[PrefixDatabase]]:
    """ksp2_every > 0 marks every Nth node's prefixes SR_MPLS +
    KSP2_ED_ECMP (a segment-routed subset over a plain-IP fabric —
    BASELINE config 4's shape); it implies node labels (label stacks
    need them)."""
    if ksp2_every:
        node_labels = True

    def algo_for(idx: int):
        if ksp2_every and idx % ksp2_every == 0:
            return (
                PrefixForwardingType.SR_MPLS,
                PrefixForwardingAlgorithm.KSP2_ED_ECMP,
            )
        if forwarding_algorithm == PrefixForwardingAlgorithm.KSP2_ED_ECMP:
            return (PrefixForwardingType.SR_MPLS, forwarding_algorithm)
        return (PrefixForwardingType.IP, forwarding_algorithm)

    adj_dbs = []
    prefix_dbs = []
    for idx, (name, adjs) in enumerate(nodes.items()):
        adj_dbs.append(
            AdjacencyDatabase(
                this_node_name=name,
                adjacencies=tuple(adjs),
                node_label=(101 + idx) if node_labels else 0,
                area=area,
            )
        )
        fwd_type, fwd_algo = algo_for(idx)
        for p in range(prefixes_per_node):
            prefix = _loopback_prefix(idx * prefixes_per_node + p + 1)
            prefix_dbs.append(
                PrefixDatabase(
                    this_node_name=name,
                    prefix_entries=(
                        PrefixEntry(
                            prefix=prefix,
                            type=PrefixType.LOOPBACK,
                            forwarding_type=fwd_type,
                            forwarding_algorithm=fwd_algo,
                        ),
                    ),
                    area=area,
                )
            )
    return adj_dbs, prefix_dbs


def grid(
    n: int,
    area: str = "0",
    forwarding_algorithm: PrefixForwardingAlgorithm = PrefixForwardingAlgorithm.SP_ECMP,
    node_labels: bool = True,
    prefixes_per_node: int = 1,
) -> tuple[list[AdjacencyDatabase], list[PrefixDatabase]]:
    """n x n grid (ref createGrid:308): node-(row,col) connects 4-ways."""
    nodes: dict[str, list[Adjacency]] = {}
    name = lambda r, c: f"node-{r}-{c}"  # noqa: E731
    for r in range(n):
        for c in range(n):
            adjs = []
            for dr, dc in ((-1, 0), (1, 0), (0, -1), (0, 1)):
                rr, cc = r + dr, c + dc
                if 0 <= rr < n and 0 <= cc < n:
                    adjs.append(_adj(name(r, c), name(rr, cc)))
            nodes[name(r, c)] = adjs
    return _mk_dbs(nodes, area, forwarding_algorithm, node_labels, prefixes_per_node)


def ring(
    n: int,
    area: str = "0",
    forwarding_algorithm: PrefixForwardingAlgorithm = PrefixForwardingAlgorithm.SP_ECMP,
    node_labels: bool = True,
) -> tuple[list[AdjacencyDatabase], list[PrefixDatabase]]:
    """Ring of n nodes (ref OpenrSystemTest RingTopology)."""
    nodes: dict[str, list[Adjacency]] = {}
    name = lambda i: f"node-{i}"  # noqa: E731
    for i in range(n):
        nodes[name(i)] = [
            _adj(name(i), name((i - 1) % n)),
            _adj(name(i), name((i + 1) % n)),
        ]
    if n == 2:  # avoid duplicate parallel links in a 2-ring
        nodes[name(0)] = [_adj(name(0), name(1))]
        nodes[name(1)] = [_adj(name(1), name(0))]
    return _mk_dbs(nodes, area, forwarding_algorithm, node_labels)


def full_mesh(
    n: int,
    area: str = "0",
    forwarding_algorithm: PrefixForwardingAlgorithm = PrefixForwardingAlgorithm.SP_ECMP,
    node_labels: bool = True,
) -> tuple[list[AdjacencyDatabase], list[PrefixDatabase]]:
    """Every node adjacent to every other (BASELINE config 1's 4-node mesh)."""
    nodes: dict[str, list[Adjacency]] = {}
    name = lambda i: f"node-{i}"  # noqa: E731
    for i in range(n):
        nodes[name(i)] = [_adj(name(i), name(j)) for j in range(n) if j != i]
    return _mk_dbs(nodes, area, forwarding_algorithm, node_labels)


def fat_tree(
    pods: int = 2,
    planes: int = 2,
    ssws_per_plane: int = 4,
    fsws_per_pod: int = 2,
    rsws_per_pod: int = 4,
    area: str = "0",
    forwarding_algorithm: PrefixForwardingAlgorithm = PrefixForwardingAlgorithm.SP_ECMP,
    node_labels: bool = True,
) -> tuple[list[AdjacencyDatabase], list[PrefixDatabase]]:
    """3-tier fabric (ref createFabric:361): ssw (spine, per plane) <-> fsw
    (fabric, per pod; fsw #p in a pod belongs to plane p) <-> rsw (rack).
    Reference production markers: 36 ssw/plane, 48 rsw/pod
    (RoutingBenchmarkUtils.h:93-99) — pass those for the big benchmark.
    """
    assert fsws_per_pod == planes or planes == 1, (
        "each pod needs one fsw per plane (fsws_per_pod == planes)"
    )
    nodes: dict[str, list[Adjacency]] = {}
    ssw = lambda pl, i: f"ssw-{pl}-{i}"  # noqa: E731
    fsw = lambda pod, pl: f"fsw-{pod}-{pl}"  # noqa: E731
    rsw = lambda pod, i: f"rsw-{pod}-{i}"  # noqa: E731

    for pl in range(planes):
        for i in range(ssws_per_plane):
            nodes[ssw(pl, i)] = [_adj(ssw(pl, i), fsw(pod, pl)) for pod in range(pods)]
    for pod in range(pods):
        for pl in range(planes):
            adjs = [_adj(fsw(pod, pl), ssw(pl, i)) for i in range(ssws_per_plane)]
            adjs += [_adj(fsw(pod, pl), rsw(pod, i)) for i in range(rsws_per_pod)]
            nodes[fsw(pod, pl)] = adjs
        for i in range(rsws_per_pod):
            nodes[rsw(pod, i)] = [
                _adj(rsw(pod, i), fsw(pod, pl)) for pl in range(planes)
            ]
    return _mk_dbs(nodes, area, forwarding_algorithm, node_labels)


def fabric(
    pods: int = 96,
    planes: int = 8,
    ssws_per_plane: int = 36,
    rsws_per_pod: int = 64,
    area: str = "0",
    forwarding_algorithm: PrefixForwardingAlgorithm = PrefixForwardingAlgorithm.SP_ECMP,
    node_labels: bool = False,
    prefixes_per_node: int = 1,
) -> tuple[list[AdjacencyDatabase], list[PrefixDatabase]]:
    """Large 3-tier fabric for benchmarks (BASELINE config 3), pod-major
    node naming so natural-sort index order keeps pods contiguous: the
    rsw<->fsw tier decomposes into shift classes on the device mirror
    (ops/edgeplan.py), the pod-crossing spine tier lands in the compact
    residual. Structure follows the reference fabric markers
    (RoutingBenchmarkUtils.h:93-99: ssw/plane, rsw/pod); one fsw per
    plane per pod."""
    nodes: dict[str, list[Adjacency]] = {}
    fsw = lambda pod, pl: f"pod{pod:03d}-fsw{pl:02d}"  # noqa: E731
    rsw = lambda pod, i: f"pod{pod:03d}-rsw{i:02d}"  # noqa: E731
    ssw = lambda pl, s: f"zspine{pl:02d}-ssw{s:02d}"  # noqa: E731

    for pod in range(pods):
        for pl in range(planes):
            adjs = [_adj(fsw(pod, pl), ssw(pl, s)) for s in range(ssws_per_plane)]
            adjs += [_adj(fsw(pod, pl), rsw(pod, i)) for i in range(rsws_per_pod)]
            nodes[fsw(pod, pl)] = adjs
        for i in range(rsws_per_pod):
            nodes[rsw(pod, i)] = [
                _adj(rsw(pod, i), fsw(pod, pl)) for pl in range(planes)
            ]
    for pl in range(planes):
        for s in range(ssws_per_plane):
            nodes[ssw(pl, s)] = [
                _adj(ssw(pl, s), fsw(pod, pl)) for pod in range(pods)
            ]
    return _mk_dbs(
        nodes, area, forwarding_algorithm, node_labels, prefixes_per_node
    )


def wan(
    regions: int = 48,
    region_side: int = 32,
    hub_links: int = 3,
    seed: int = 7,
    area: str = "0",
    forwarding_algorithm: PrefixForwardingAlgorithm = PrefixForwardingAlgorithm.SP_ECMP,
    node_labels: bool = False,
    ksp2_every: int = 0,
) -> tuple[list[AdjacencyDatabase], list[PrefixDatabase]]:
    """Multi-region WAN for benchmarks (BASELINE config 4): each region is
    a metro grid (region-major naming keeps intra-region edges in shared
    shift classes); per-region hub routers interconnect over a region ring
    plus random chords with higher metrics (long-haul)."""
    rng = random.Random(seed)
    nodes: dict[str, list[Adjacency]] = {}
    name = lambda g, r, c: f"r{g:02d}-n{r:02d}-{c:02d}"  # noqa: E731
    for g in range(regions):
        for r in range(region_side):
            for c in range(region_side):
                adjs = []
                for dr, dc in ((-1, 0), (1, 0), (0, -1), (0, 1)):
                    rr, cc = r + dr, c + dc
                    if 0 <= rr < region_side and 0 <= cc < region_side:
                        adjs.append(_adj(name(g, r, c), name(g, rr, cc)))
                nodes[name(g, r, c)] = adjs
    # inter-region: hubs at the region center; ring + chords
    mid = region_side // 2
    hub = lambda g: name(g, mid, mid)  # noqa: E731
    pairs = {
        (min(g, (g + 1) % regions), max(g, (g + 1) % regions))
        for g in range(regions)
    }
    # target bounded by the number of distinct hub pairs, else few-region
    # configs loop forever asking for more chords than exist
    target = min(regions * hub_links // 2, regions * (regions - 1) // 2)
    while len(pairs) < target:
        a, b = rng.randrange(regions), rng.randrange(regions)
        if a != b:
            pairs.add((min(a, b), max(a, b)))
    for a, b in pairs:
        metric = rng.randint(10, 100)
        nodes[hub(a)].append(_adj(hub(a), hub(b), metric=metric))
        nodes[hub(b)].append(_adj(hub(b), hub(a), metric=metric))
    return _mk_dbs(
        nodes, area, forwarding_algorithm, node_labels, ksp2_every=ksp2_every
    )


def random_mesh(
    n: int,
    avg_degree: int = 4,
    seed: int = 0,
    area: str = "0",
    forwarding_algorithm: PrefixForwardingAlgorithm = PrefixForwardingAlgorithm.SP_ECMP,
    node_labels: bool = False,
) -> tuple[list[AdjacencyDatabase], list[PrefixDatabase]]:
    """Connected random graph (Terragraph-style wireless mesh stand-in,
    BASELINE config 2): ring backbone + random chords to reach avg_degree."""
    rng = random.Random(seed)
    name = lambda i: f"node-{i}"  # noqa: E731
    edges: set[tuple[int, int]] = set()
    for i in range(n):
        edges.add((min(i, (i + 1) % n), max(i, (i + 1) % n)))
    target_edges = n * avg_degree // 2
    while len(edges) < target_edges:
        a, b = rng.randrange(n), rng.randrange(n)
        if a != b:
            edges.add((min(a, b), max(a, b)))
    adjacency: dict[int, list[int]] = {i: [] for i in range(n)}
    for a, b in edges:
        adjacency[a].append(b)
        adjacency[b].append(a)
    nodes = {
        name(i): [_adj(name(i), name(j), metric=1) for j in sorted(neighbors)]
        for i, neighbors in adjacency.items()
    }
    return _mk_dbs(nodes, area, forwarding_algorithm, node_labels)


def multi_area(
    regions: int = 3,
    side: int = 4,
    backbone_area: str = "bb",
    forwarding_algorithm: PrefixForwardingAlgorithm = PrefixForwardingAlgorithm.SP_ECMP,
) -> tuple[list[AdjacencyDatabase], list[PrefixDatabase]]:
    """Multi-area topology (ref openr/docs/Features/Area.md; per-area
    KvStoreDb/LinkState): each region is its own flooding domain (area
    "r<i>") of a side x side grid; the region hubs additionally belong
    to a backbone area ring. Hub nodes therefore carry TWO adjacency
    databases (one per area) — the shape Decision's per-area LinkState
    map models. Loopbacks announce in the node's region area; hubs also
    announce a backbone-scoped prefix in the backbone area."""
    adj_dbs: list[AdjacencyDatabase] = []
    prefix_dbs: list[PrefixDatabase] = []
    name = lambda g, r, c: f"r{g:02d}-n{r:02d}-{c:02d}"  # noqa: E731
    mid = side // 2
    hub = lambda g: name(g, mid, mid)  # noqa: E731

    idx = 0
    for g in range(regions):
        area = f"r{g}"
        for r in range(side):
            for c in range(side):
                adjs = []
                for dr, dc in ((-1, 0), (1, 0), (0, -1), (0, 1)):
                    rr, cc = r + dr, c + dc
                    if 0 <= rr < side and 0 <= cc < side:
                        adjs.append(_adj(name(g, r, c), name(g, rr, cc)))
                idx += 1
                adj_dbs.append(
                    AdjacencyDatabase(
                        this_node_name=name(g, r, c),
                        adjacencies=tuple(adjs),
                        node_label=100 + idx,
                        area=area,
                    )
                )
                prefix_dbs.append(
                    PrefixDatabase(
                        this_node_name=name(g, r, c),
                        prefix_entries=(
                            PrefixEntry(
                                prefix=_loopback_prefix(idx),
                                type=PrefixType.LOOPBACK,
                                forwarding_type=PrefixForwardingType.IP,
                                forwarding_algorithm=forwarding_algorithm,
                            ),
                        ),
                        area=area,
                    )
                )
    # backbone: hub ring with long-haul metrics + hub backbone prefixes
    for g in range(regions):
        nbrs = []
        for other in ((g - 1) % regions, (g + 1) % regions):
            if other != g:
                nbrs.append(_adj(hub(g), hub(other), metric=10))
        adj_dbs.append(
            AdjacencyDatabase(
                this_node_name=hub(g),
                adjacencies=tuple(dict.fromkeys(nbrs)),
                node_label=5000 + g,
                area=backbone_area,
            )
        )
        prefix_dbs.append(
            PrefixDatabase(
                this_node_name=hub(g),
                prefix_entries=(
                    PrefixEntry(
                        prefix=f"fd00:bb::{g:x}/128",
                        type=PrefixType.LOOPBACK,
                        forwarding_type=PrefixForwardingType.IP,
                        forwarding_algorithm=forwarding_algorithm,
                    ),
                ),
                area=backbone_area,
            )
        )
    return adj_dbs, prefix_dbs
