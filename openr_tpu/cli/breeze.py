"""breeze — the operator CLI.

Role of the reference's openr/py/openr/cli/breeze.py (:32) click CLI:
subcommand groups per module (kvstore, decision, fib, lm, spark,
prefixmgr, monitor, openr, perf, tech-support) talking to the ctrl server
(ref get_openr_ctrl_client, openr/py/openr/clients/openr_client.py:94).

Usage:  python -m openr_tpu.cli.breeze --port <ctrl-port> <group> <cmd>
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Optional

import click

from openr_tpu.runtime.rpc import RpcClient


def _call(
    ctx, method: str, params: Optional[dict] = None, timeout_s: float = 30.0
) -> Any:
    """One-shot RPC against the ctrl server."""

    async def run():
        client = RpcClient(
            ctx.obj["host"], ctx.obj["port"], name="breeze",
            ssl=ctx.obj.get("ssl"),
            expected_peer=ctx.obj.get("peer_name", ""),
        )
        try:
            return await client.request(method, params or {}, timeout_s)
        finally:
            await client.close()

    return asyncio.run(run())


def _print(obj: Any) -> None:
    click.echo(json.dumps(obj, indent=2, sort_keys=True, default=str))


@click.group()
@click.option("--host", default="127.0.0.1", help="ctrl server host")
@click.option("--port", default=2018, type=int, help="ctrl server port")
@click.option("--cacert", default="", help="CA bundle: verify + TLS on")
@click.option("--cert", default="", help="client certificate (mutual TLS)")
@click.option("--key", default="", help="client private key")
@click.option(
    "--peer-name", default="",
    help="node name the server cert must claim (CN/SAN identity pin)",
)
@click.pass_context
def cli(
    ctx, host: str, port: int, cacert: str, cert: str, key: str,
    peer_name: str,
) -> None:
    """breeze — operate an openr_tpu node (ref breeze.py:32)."""
    ctx.ensure_object(dict)
    ctx.obj["host"] = host
    ctx.obj["port"] = port
    ctx.obj["ssl"] = None
    ctx.obj["peer_name"] = peer_name
    if cacert or cert or key:
        from openr_tpu.config import build_client_ssl_context

        ctx.obj["ssl"] = build_client_ssl_context(cacert, cert, key)


# -- openr ------------------------------------------------------------------

@cli.group()
def openr() -> None:
    """Node-level info."""


@openr.command()
@click.pass_context
def version(ctx) -> None:
    _print(_call(ctx, "openr.version"))


@openr.command("initialization")
@click.pass_context
def initialization(ctx) -> None:
    """Cold-boot convergence milestones (ref getInitializationEvents)."""
    _print(_call(ctx, "openr.initialization_events"))


@openr.command("subscribers")
@click.option("--type", "sub_type", default="", help="kvstore / fib / fib_detail")
@click.pass_context
def subscribers(ctx, sub_type) -> None:
    """Live streaming-subscription stats (ref getSubscriberInfo)."""
    _print(_call(ctx, "ctrl.subscriber_info", {"type": sub_type}))


# -- kvstore ----------------------------------------------------------------

@cli.group()
def kvstore() -> None:
    """Replicated key-value store."""


@kvstore.command()
@click.argument("keys", nargs=-1)
@click.option("--area", default="0")
@click.pass_context
def keys(ctx, keys, area) -> None:
    """Fetch specific keys."""
    _print(_call(ctx, "ctrl.kvstore.keyvals", {"area": area, "keys": list(keys)}))


@kvstore.command()
@click.option("--prefix", default="", help="key prefix filter")
@click.option("--area", default="0")
@click.pass_context
def dump(ctx, prefix, area) -> None:
    """Dump all key/values."""
    _print(_call(ctx, "ctrl.kvstore.dump", {"area": area, "prefix": prefix}))


@kvstore.command()
@click.option("--area", default="0")
@click.pass_context
def peers(ctx, area) -> None:
    """Peer sessions and sync states."""
    _print(_call(ctx, "ctrl.kvstore.peers", {"area": area}))


@kvstore.command("set-key")
@click.argument("key")
@click.argument("value")
@click.option("--area", default="0")
@click.option("--ttl", "ttl_ms", type=int, default=None,
              help="finite ttl in ms (default: infinite)")
@click.pass_context
def kv_set_key(ctx, key, value, area, ttl_ms) -> None:
    """Inject a key (version auto-bumps to win; ref setKvStoreKeyVals)."""
    _print(_call(ctx, "ctrl.kvstore.set_key",
                 {"key": key, "value": value, "area": area,
                  "ttl_ms": ttl_ms}))


@kvstore.command("hashes")
@click.option("--prefix", default="")
@click.option("--area", default="0")
@click.pass_context
def kv_hashes(ctx, prefix, area) -> None:
    """Hash-only dump (ref getKvStoreHashFiltered)."""
    _print(_call(ctx, "ctrl.kvstore.hashes",
                 {"prefix": prefix, "area": area}))


@kvstore.command("areas")
@click.pass_context
def kv_areas(ctx) -> None:
    """Per-area summary (ref getKvStoreAreaSummary)."""
    _print(_call(ctx, "ctrl.kvstore.areas"))


@kvstore.command("flood-topo")
@click.option("--area", default="0")
@click.pass_context
def flood_topo(ctx, area) -> None:
    """DUAL spanning-tree flooding state."""
    _print(_call(ctx, "ctrl.kvstore.flood_topo", {"area": area}))


@kvstore.command("divergence")
@click.option("--no-resolve", is_flag=True,
              help="skip pulling suspects' key hashes (digest compare only)")
@click.pass_context
def kv_divergence(ctx, no_resolve) -> None:
    """LSDB divergence check: compare peers' lsdb-digest beacons
    against our recent local digests; by default each suspect peer is
    interrogated for the first divergent key."""
    _print(_call(ctx, "ctrl.kvstore.divergence",
                 {"resolve": not no_resolve}))


@kvstore.command("nodes")
@click.option("--area", default="0")
@click.pass_context
def kv_nodes(ctx, area) -> None:
    """Node names present in the LSDB (ref breeze kvstore nodes):
    derived from adj:/prefix: keys."""
    from openr_tpu.types import parse_adj_key, parse_prefix_key

    dump = _call(ctx, "ctrl.kvstore.dump", {"area": area})
    nodes: dict[str, dict] = {}
    for key in dump:
        adj = parse_adj_key(key)
        if adj:
            nodes.setdefault(adj, {"adj": False, "prefixes": 0})["adj"] = True
        parsed = parse_prefix_key(key)
        if parsed:
            n = nodes.setdefault(
                parsed[0], {"adj": False, "prefixes": 0}
            )
            n["prefixes"] += 1
    _print(nodes)


@kvstore.command("snoop")
@click.option("--area", default="0")
@click.option("--duration", default=0.0, type=float,
              help="seconds to snoop; 0 = forever")
@click.option("--no-snapshot", is_flag=True,
              help="skip the initial full dump, print deltas only")
@click.pass_context
def kv_snoop(ctx, area, duration, no_snapshot) -> None:
    """Live-print KvStore deltas as they flood (ref breeze kvstore
    snoop, clis/kvstore.py SnoopCli — on the streaming subscription)."""
    import time as _time

    async def run():
        client = RpcClient(
            ctx.obj["host"], ctx.obj["port"], name="breeze",
            ssl=ctx.obj.get("ssl"),
            expected_peer=ctx.obj.get("peer_name", ""),
        )
        try:
            q = await client.subscribe(
                "ctrl.kvstore.subscribe", {"area": area}
            )
            deadline = (
                _time.monotonic() + duration if duration > 0 else None
            )
            while True:
                remaining = (
                    None if deadline is None
                    else deadline - _time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return
                try:
                    item = await asyncio.wait_for(q.get(), remaining)
                except asyncio.TimeoutError:
                    return
                if isinstance(item, Exception):
                    raise item
                if item is None:
                    return  # stream closed
                if "snapshot" in item:
                    if not no_snapshot:
                        click.echo(json.dumps(
                            {"snapshot_keys": sorted(item["snapshot"])},
                            default=str,
                        ))
                    continue
                click.echo(json.dumps(item, sort_keys=True, default=str))
        finally:
            await client.close()

    asyncio.run(run())


@kvstore.command("kv-compare")
@click.option("--nodes", required=True,
              help="comma-separated host:port ctrl endpoints to compare "
              "against this node")
@click.option("--peer-names", default="",
              help="comma-separated TLS identity pins for --nodes (same "
              "order); the local node uses --peer-name")
@click.option("--area", default="0")
@click.pass_context
def kv_compare(ctx, nodes, peer_names, area) -> None:
    """Diff this node's store against other nodes' (ref breeze kvstore
    kv-compare): missing keys and per-key divergence over (version,
    originator, ttl_version, value hash) — two stores that agree on
    version+originator can still hold different payloads after a
    partition heal, and a ttl_version skew means refreshes are not
    propagating. Exit code 1 on any delta."""
    specs = [s.strip() for s in nodes.split(",") if s.strip()]
    pins = [p.strip() for p in peer_names.split(",")] if peer_names else []
    if pins and len(pins) != len(specs):
        raise click.UsageError(
            f"--peer-names has {len(pins)} entries for {len(specs)} nodes"
        )
    targets = []
    for i, spec in enumerate(specs):
        host, _, port = spec.rpartition(":")
        if not port.isdigit():
            raise click.UsageError(
                f"--nodes entry {spec!r} is not host:port"
            )
        targets.append(
            (spec, host or "127.0.0.1", int(port), pins[i] if pins else "")
        )
    if not targets:
        raise click.UsageError("--nodes is empty")

    async def dump_of(host, port, pin):
        client = RpcClient(
            host, port, name="breeze",
            ssl=ctx.obj.get("ssl"),
            expected_peer=pin,
        )
        try:
            return await client.request(
                "ctrl.kvstore.dump", {"area": area}
            )
        finally:
            await client.close()

    async def run():
        mine = await dump_of(
            ctx.obj["host"], ctx.obj["port"],
            ctx.obj.get("peer_name", ""),
        )
        report = {}
        for spec, host, port, pin in targets:
            theirs = await dump_of(host, port, pin)

            def ident(v):
                import hashlib

                val = v.get("value")
                if isinstance(val, dict) and "__bytes__" in val:
                    payload = bytes.fromhex(val["__bytes__"])
                elif val is None:
                    payload = b""
                else:
                    payload = json.dumps(
                        val, sort_keys=True, default=str
                    ).encode()
                return (
                    v.get("version"),
                    v.get("originator_id"),
                    v.get("ttl_version"),
                    hashlib.sha256(payload).hexdigest(),
                )

            delta = {
                "missing_there": sorted(set(mine) - set(theirs)),
                "missing_here": sorted(set(theirs) - set(mine)),
                "diverged": sorted(
                    k
                    for k in set(mine) & set(theirs)
                    if ident(mine[k]) != ident(theirs[k])
                ),
            }
            delta["ok"] = not any(delta.values())
            report[spec] = delta
        return report

    report = asyncio.run(run())
    _print(report)
    if not all(r["ok"] for r in report.values()):
        raise SystemExit(1)


@kvstore.command("long-poll-adj")
@click.option("--area", default="0")
@click.option(
    "--snapshot",
    default="{}",
    help='JSON {"adj:node": version, ...} the caller last saw',
)
@click.option("--timeout", default=290.0, type=float)
@click.pass_context
def long_poll_adj(ctx, area, snapshot, timeout) -> None:
    """Block until any adjacency key changes vs the snapshot."""
    _print(
        _call(
            ctx,
            "ctrl.kvstore.long_poll_adj",
            {
                "area": area,
                "snapshot": json.loads(snapshot),
                "timeout_s": timeout,
            },
            timeout_s=timeout + 10,
        )
    )


# `openr config` / `openr dryrun-config` alias the config group's
# show/dryrun — one implementation, two spellings (the reference keeps
# config under its own group; the openr group spelling predates ours)


@openr.command("drain-state")
@click.pass_context
def drain_state(ctx) -> None:
    """Node drain + per-link overrides (ref getDrainState)."""
    _print(_call(ctx, "openr.drain_state"))


# -- decision ---------------------------------------------------------------

@cli.group()
def decision() -> None:
    """Route computation."""


@decision.command()
@click.option("--from-node", default=None, help="compute from another node's view")
@click.pass_context
def routes(ctx, from_node) -> None:
    _print(_call(ctx, "ctrl.decision.routes", {"from_node": from_node}))


@decision.command("fabric-routes")
@click.option(
    "--nodes",
    default=None,
    help="comma-separated vantage nodes (default: every node in the LSDB)",
)
@click.pass_context
def fabric_routes(ctx, nodes) -> None:
    """Every vantage's RIB in one sharded device pass."""
    _print(
        _call(
            ctx,
            "ctrl.decision.fabric_routes",
            {"from_nodes": nodes.split(",") if nodes else None},
        )
    )


@decision.command()
@click.pass_context
def adjacencies(ctx) -> None:
    _print(_call(ctx, "ctrl.decision.adj_dbs"))


@decision.command("path")
@click.argument("src")
@click.argument("dst")
@click.option("--area", default="", help="restrict to one area")
@click.option("--k", default=2, help="edge-disjoint paths per area")
@click.pass_context
def decision_path(ctx, src, dst, area, k) -> None:
    """Paths between two nodes from the live LSDB (ref breeze decision
    path)."""
    _print(_call(ctx, "ctrl.decision.path",
                 {"src": src, "dst": dst, "area": area, "k": k}))


@decision.command("explain")
@click.argument("prefix")
@click.pass_context
def decision_explain(ctx, prefix) -> None:
    """Route provenance: which kvstore event (key / originator / area)
    put this route in the RIB, the solve epoch that materialized it,
    which solver kind ran (full / incremental / failover-cpu), and
    whether the Fib agent has it programmed."""
    _print(_call(ctx, "ctrl.decision.explain", {"prefix": prefix}))


@decision.command("validate")
@click.pass_context
def decision_validate(ctx) -> None:
    """Cross-check Decision's LSDB view against KvStore's keys (ref
    breeze decision validate). Exit code 1 on any delta."""
    report = _call(ctx, "ctrl.decision.validate")
    _print(report)
    if not all(area["ok"] for area in report.values()):
        raise SystemExit(1)


@decision.command("received-routes")
@click.pass_context
def received_routes(ctx) -> None:
    _print(_call(ctx, "ctrl.decision.received_routes"))


@decision.command("convergence")
@click.option(
    "--fleet",
    is_flag=True,
    help="add the fleet view: per-origin-event convergence aggregated "
    "from every node's conv-ack ring, with straggler attribution",
)
@click.pass_context
def decision_convergence(ctx, fleet) -> None:
    """Per-event convergence latency: p50/p95/p99 over closed traces,
    the windowed convergence_ms stat, and the solver's incremental vs
    full dispatch split (incremental_solves / incremental_full_fallbacks
    / full_solves plus cone-fraction and changed-row stats). With
    --fleet, each origin event's origin→last-FIB-ack latency across the
    whole fleet plus the straggler node."""
    _print(_call(ctx, "ctrl.decision.convergence", {"fleet": fleet}))


@decision.command("replay")
@click.pass_context
def decision_replay(ctx) -> None:
    """Input black-box recorder + RIB-digest status: current solve
    epoch, per-epoch and rolling RIB digests, recorder ring fill,
    snapshot anchor (cursor + base epoch), and the digest-ledger tail.
    Bit-compare the rolling digest across replicas to localize a
    RIB-level divergence; replay a recorded bundle offline with
    `python -m tools.replay` (docs/Observability.md § Record &
    replay)."""
    _print(_call(ctx, "ctrl.decision.replay"))


@decision.command("overload")
@click.pass_context
def decision_overload(ctx) -> None:
    """Overload ladder + flap damper: current state
    (ok/backpressure/brownout/shedding), the signals driving it (queue
    depth, HBM fraction, RSS, SLO burn), suppressed keys with their
    decayed figures of merit, shed/rejection counts, and the recent
    transition history (docs/Operations.md § Overload control)."""
    _print(_call(ctx, "ctrl.decision.overload"))


@decision.command("budget")
@click.option(
    "--fleet",
    is_flag=True,
    help="join the fleet conv-ack view: per-origin-event convergence "
    "with the straggler's dominant budget COMPONENT named",
)
@click.option(
    "--raw", is_flag=True, help="full JSON report instead of the waterfall"
)
@click.option(
    "--window",
    default="600",
    help="stat window in seconds for the percentile columns (60/600/3600)",
)
@click.pass_context
def decision_budget(ctx, fleet, raw, window) -> None:
    """Churn-to-ack latency budget waterfall: every epoch decomposed
    into the canonical component taxonomy (ingest_wait .. ack_rtt) with
    a conservation invariant — components sum to measured e2e, residual
    exported as budget.unattributed_ms. Names which component owns the
    p50→p99 tail."""
    rep = _call(ctx, "ctrl.decision.budget", {"fleet": fleet})
    if raw:
        _print(rep)
        return

    def _agg(win: dict) -> dict:
        if not isinstance(win, dict) or not win:
            return {}
        return win.get(window) or next(iter(win.values()), {}) or {}

    e2e = _agg(rep.get("e2e"))
    e2e_p99 = float(e2e.get("p99") or 0.0)
    click.echo(
        f"latency budget — node {rep.get('node', '?')}  "
        f"(window {window}s, epochs {rep.get('conservation', {}).get('epochs') or 0})"
    )
    click.echo(
        f"{'component':<16}{'p50':>9}{'p95':>9}{'p99':>9}  share(p99)"
    )
    for comp in rep.get("taxonomy", []):
        agg = _agg(rep.get("components", {}).get(comp))
        if not agg or not agg.get("count"):
            continue
        p99 = float(agg.get("p99") or 0.0)
        share = (p99 / e2e_p99) if e2e_p99 > 0 else 0.0
        bar = "#" * max(0, min(30, int(round(share * 30))))
        click.echo(
            f"{comp:<16}{agg.get('p50', 0.0):>9.3f}"
            f"{agg.get('p95', 0.0):>9.3f}{p99:>9.3f}  "
            f"{bar} {share * 100.0:.0f}%"
        )
    click.echo(
        f"{'e2e':<16}{e2e.get('p50', 0.0):>9.3f}"
        f"{e2e.get('p95', 0.0):>9.3f}{e2e_p99:>9.3f}"
    )
    un = _agg(rep.get("unattributed"))
    un_p99 = float(un.get("p99") or 0.0)
    pct = (100.0 * un_p99 / e2e_p99) if e2e_p99 > 0 else 0.0
    click.echo(
        f"{'unattributed':<16}{un.get('p50', 0.0):>9.3f}"
        f"{un.get('p95', 0.0):>9.3f}{un_p99:>9.3f}  "
        f"({pct:.1f}% of e2e p99 — conservation "
        f"{'OK' if pct < 5.0 else 'DRIFTING'})"
    )
    tail = rep.get("tail") or {}
    ranked = tail.get("ranked") or []
    if ranked:
        named = ", ".join(
            f"{r['component']} +{r['gap_ms']:.3f}ms" for r in ranked[:2]
        )
        cov = tail.get("top2_coverage")
        cov_s = f" (top-2 cover {cov * 100.0:.0f}% of gap)" if cov else ""
        click.echo(
            f"p50→p99 tail: {named}{cov_s}"
        )
    if fleet and rep.get("fleet"):
        click.echo("\nfleet events (straggler node → component):")
        for ev in rep["fleet"].get("events", [])[:10]:
            comp = ev.get("straggler_component")
            comp_s = (
                f" [{comp} {ev.get('straggler_component_ms', 0.0):.3f}ms]"
                if comp
                else ""
            )
            click.echo(
                f"  {ev['event']}: {ev['fleet_ms']:.3f}ms "
                f"straggler={ev['straggler']}{comp_s} "
                f"({ev['nodes_acked']} acked)"
            )


@decision.command("rib-policy")
@click.option("--clear", is_flag=True, help="remove the active policy")
@click.option(
    "--set",
    "set_json",
    default=None,
    help="install a policy from JSON (statements + ttl_secs)",
)
@click.pass_context
def rib_policy(ctx, clear, set_json) -> None:
    if clear:
        _print(_call(ctx, "ctrl.decision.clear_rib_policy"))
    elif set_json is not None:
        _print(
            _call(
                ctx,
                "ctrl.decision.set_rib_policy",
                {"policy": json.loads(set_json)},
            )
        )
    else:
        _print(_call(ctx, "ctrl.decision.get_rib_policy"))


# -- decision whatif --------------------------------------------------------

@decision.group("whatif")
def whatif() -> None:
    """Hypothetical-topology sweeps on the resident device graph."""


@whatif.command("sweep")
@click.option("--order", default=1, help="failure order: 1 (N-1) or 2 (N-2)")
@click.option("--area", default="", help="restrict to one area")
@click.option(
    "--roots", default=None,
    help="comma-separated vantage nodes (default: this node)",
)
@click.option(
    "--max-scenarios", default=0,
    help="cap the scenario count (0 = all; N-2 is quadratic)",
)
@click.option("--top", default=0, help="only print the worst N scenarios")
@click.pass_context
def whatif_sweep(ctx, order, area, roots, max_scenarios, top) -> None:
    """Batched N-k link-failure sweep: which failures partition or
    stretch the fabric, judged against the live baseline in one
    vmapped device dispatch."""
    _print(_call(ctx, "ctrl.decision.whatif.sweep", {
        "order": order,
        "area": area,
        "roots": roots.split(",") if roots else None,
        "max_scenarios": max_scenarios,
        "top": top,
    }))


@whatif.command("drain")
@click.option("--node", default="", help="preview draining this node")
@click.option("--link", default="", help="preview draining link 'n1|n2'")
@click.option("--area", default="", help="restrict to one area")
@click.option("--top", default=10, help="most-affected destinations to list")
@click.pass_context
def whatif_drain(ctx, node, link, area, top) -> None:
    """Impact preview before an operator drains a node or link."""
    _print(_call(ctx, "ctrl.decision.whatif.drain", {
        "node": node, "link": link, "area": area, "top": top,
    }))


@whatif.command("optimize")
@click.option(
    "--demand", "demand_json", required=True,
    help='demand matrix JSON: [{"src": ..., "dst": ..., "volume": ...}]',
)
@click.option("--area", default="", help="restrict to one area")
@click.option("--iters", default=40, help="gradient-descent iterations")
@click.option("--lr", default=2.0, help="gradient-descent step size")
@click.option("--tau", default=1.0, help="softmin temperature")
@click.pass_context
def whatif_optimize(ctx, demand_json, area, iters, lr, tau) -> None:
    """Differentiable link-weight TE: propose a metric vector lowering
    the predicted max link utilization for a demand matrix."""
    _print(_call(ctx, "ctrl.decision.whatif.optimize", {
        "demands": json.loads(demand_json),
        "area": area, "iters": iters, "lr": lr, "tau": tau,
    }))


# -- fib --------------------------------------------------------------------

@cli.group()
def fib() -> None:
    """Programmed routes."""


@fib.command("routes")
@click.pass_context
def fib_routes(ctx) -> None:
    _print(_call(ctx, "ctrl.fib.routes"))


@fib.command("mpls-routes")
@click.pass_context
def fib_mpls(ctx) -> None:
    _print(_call(ctx, "ctrl.fib.mpls_routes"))


@fib.command("route-detail")
@click.pass_context
def fib_route_detail(ctx) -> None:
    """Programmed routes with selection detail (ref getRouteDetailDb)."""
    _print(_call(ctx, "ctrl.fib.route_detail_db"))


@fib.command("validate")
@click.pass_context
def fib_validate(ctx) -> None:
    """Decision's computed routes vs Fib's programmed state (ref breeze
    fib validate). Exit code 1 on any persistent delta."""
    report = _call(ctx, "ctrl.fib.validate")
    _print(report)
    if not report["ok"]:
        raise SystemExit(1)


# -- perf -------------------------------------------------------------------

@cli.group()
def perf() -> None:
    """Convergence tracing."""


@perf.command("fib")
@click.pass_context
def perf_fib(ctx) -> None:
    """Per-event hop timing through the pipeline (ref commands/perf.py)."""
    for sample in _call(ctx, "ctrl.fib.perf"):
        events = sample.get("events", [])
        if not events:
            continue
        base = events[0]["unix_ts_ms"]
        click.echo("--")
        for ev in events:
            click.echo(
                f"  {ev['event_descr']:<24} {ev['node_name']:<12} "
                f"+{ev['unix_ts_ms'] - base} ms"
            )


# -- lm ---------------------------------------------------------------------

@cli.group()
def lm() -> None:
    """Link monitor."""


@lm.command()
@click.pass_context
def links(ctx) -> None:
    _print(_call(ctx, "ctrl.lm.links"))


@lm.command()
@click.pass_context
def interfaces(ctx) -> None:
    _print(_call(ctx, "ctrl.lm.interfaces"))


@lm.command("set-node-overload")
@click.pass_context
def set_node_overload(ctx) -> None:
    """Drain: stop transit traffic through this node."""
    _print(_call(ctx, "ctrl.lm.set_node_overload", {"overloaded": True}))


@lm.command("unset-node-overload")
@click.pass_context
def unset_node_overload(ctx) -> None:
    _print(_call(ctx, "ctrl.lm.set_node_overload", {"overloaded": False}))


@lm.command("set-link-metric")
@click.argument("if_name")
@click.argument("metric", type=int)
@click.pass_context
def set_link_metric(ctx, if_name, metric) -> None:
    _print(
        _call(
            ctx,
            "ctrl.lm.set_link_metric",
            {"if_name": if_name, "metric": metric},
        )
    )


@lm.command("set-adj-metric")
@click.argument("if_name")
@click.argument("neighbor")
@click.argument("metric", type=int)
@click.pass_context
def set_adj_metric(ctx, if_name, neighbor, metric) -> None:
    """Override ONE adjacency's metric (ref setAdjacencyMetric)."""
    _print(_call(ctx, "ctrl.lm.set_adj_metric",
                 {"if_name": if_name, "neighbor": neighbor,
                  "metric": metric}))


@lm.command("unset-adj-metric")
@click.argument("if_name")
@click.argument("neighbor")
@click.pass_context
def unset_adj_metric(ctx, if_name, neighbor) -> None:
    _print(_call(ctx, "ctrl.lm.set_adj_metric",
                 {"if_name": if_name, "neighbor": neighbor}))


@lm.command("unset-link-metric")
@click.argument("if_name")
@click.pass_context
def unset_link_metric(ctx, if_name) -> None:
    _print(_call(ctx, "ctrl.lm.set_link_metric", {"if_name": if_name}))


@lm.command("set-node-metric-inc")
@click.argument("increment", type=int)
@click.pass_context
def set_node_metric_inc(ctx, increment) -> None:
    """Soft-drain metric increment; 0 unsets."""
    _print(_call(ctx, "ctrl.lm.set_node_metric_increment",
                 {"increment": increment}))


@lm.command("set-link-metric-inc")
@click.argument("if_name")
@click.argument("increment", type=int)
@click.pass_context
def set_link_metric_inc(ctx, if_name, increment) -> None:
    """Per-interface metric increment; 0 unsets."""
    _print(_call(ctx, "ctrl.lm.set_link_metric_increment",
                 {"if_name": if_name, "increment": increment}))


@lm.command("adjacencies")
@click.option("--area", default=None)
@click.pass_context
def lm_adjacencies(ctx, area) -> None:
    """Advertised adjacency DBs (ref getLinkMonitorAdjacencies)."""
    _print(_call(ctx, "ctrl.lm.adjacencies", {"area": area}))


# -- spark ------------------------------------------------------------------

@cli.group()
def spark() -> None:
    """Neighbor discovery."""


@spark.command()
@click.pass_context
def neighbors(ctx) -> None:
    _print(_call(ctx, "ctrl.spark.neighbors"))


@spark.command("flood-restarting")
@click.pass_context
def flood_restarting(ctx) -> None:
    """Send graceful-restart hellos now (ref floodRestartingMsg)."""
    _print(_call(ctx, "ctrl.spark.flood_restarting"))


# -- prefixmgr --------------------------------------------------------------

@cli.group()
def prefixmgr() -> None:
    """Prefix advertisement."""


@prefixmgr.command()
@click.pass_context
def advertised(ctx) -> None:
    _print(_call(ctx, "ctrl.prefixmgr.advertised"))


@prefixmgr.command("view")
@click.pass_context
def view(ctx) -> None:
    _print(_call(ctx, "ctrl.prefixmgr.prefixes"))


@prefixmgr.command("advertise")
@click.argument("prefixes", nargs=-1, required=True)
@click.option("--prefix-type", default="BREEZE")
@click.pass_context
def pm_advertise(ctx, prefixes, prefix_type) -> None:
    """Inject prefixes network-wide (ref advertisePrefixes)."""
    _print(_call(ctx, "ctrl.prefixmgr.advertise",
                 {"prefixes": list(prefixes), "ptype": prefix_type}))


@prefixmgr.command("withdraw")
@click.argument("prefixes", nargs=-1, required=True)
@click.option("--prefix-type", default="BREEZE")
@click.pass_context
def pm_withdraw(ctx, prefixes, prefix_type) -> None:
    """Withdraw injected prefixes (ref withdrawPrefixes)."""
    _print(_call(ctx, "ctrl.prefixmgr.withdraw",
                 {"prefixes": list(prefixes), "ptype": prefix_type}))


@prefixmgr.command("withdraw-by-type")
@click.argument("prefix_type")
@click.pass_context
def pm_withdraw_by_type(ctx, prefix_type) -> None:
    _print(_call(ctx, "ctrl.prefixmgr.withdraw_by_type",
                 {"ptype": prefix_type}))


@prefixmgr.command("sync-by-type")
@click.argument("prefix_type")
@click.argument("prefixes", nargs=-1)
@click.pass_context
def pm_sync_by_type(ctx, prefix_type, prefixes) -> None:
    """Replace the full set of a type (ref syncPrefixesByType)."""
    _print(_call(ctx, "ctrl.prefixmgr.sync_by_type",
                 {"prefixes": list(prefixes), "ptype": prefix_type}))


@prefixmgr.command("originated")
@click.pass_context
def pm_originated(ctx) -> None:
    """Config-originated supernodes (ref getOriginatedPrefixes)."""
    _print(_call(ctx, "ctrl.prefixmgr.originated"))


# -- config -----------------------------------------------------------------

@cli.group("config")
def config_group() -> None:
    """Running config + persistent store (ref breeze config)."""


@config_group.command("show")
@click.pass_context
def config_show(ctx) -> None:
    """The node's running config (ref getRunningConfig)."""
    _print(_call(ctx, "ctrl.config.get"))


@config_group.command("dryrun")
@click.argument("config_file", type=click.Path(exists=True))
@click.pass_context
def config_dryrun(ctx, config_file) -> None:
    """Validate a config file against the live daemon's schema."""
    with open(config_file) as f:
        payload = json.load(f)
    _print(_call(ctx, "ctrl.config.dryrun", {"config": payload}))


@config_group.command("compare")
@click.argument("config_file", type=click.Path(exists=True))
@click.pass_context
def config_compare(ctx, config_file) -> None:
    """Diff the running config against a file (ref breeze config
    compare): both sides normalize through the daemon's parser, so
    defaults don't show as differences. Exit 1 = configs differ;
    exit 2 = could not compare (invalid file / node has no config)."""
    with open(config_file) as f:
        payload = json.load(f)
    parsed = _call(ctx, "ctrl.config.dryrun", {"config": payload})
    if not parsed.get("ok"):
        raise click.UsageError(f"file invalid: {parsed.get('error')}")
    running = _call(ctx, "ctrl.config.get")
    if not running:
        raise click.UsageError(
            "node has no running config to compare against"
        )
    candidate = parsed["config"]

    def walk(a, b, path=""):
        if isinstance(a, dict) and isinstance(b, dict):
            diffs = []
            for k in sorted(set(a) | set(b)):
                diffs += walk(a.get(k), b.get(k), f"{path}.{k}" if path else k)
            return diffs
        # dict-vs-null (optional sections) and every scalar/list case
        return [] if a == b else [{"key": path, "running": a, "file": b}]

    diffs = walk(running, candidate)
    _print({"differences": diffs, "ok": not diffs})
    if diffs:
        raise SystemExit(1)


@config_group.command("store")
@click.argument("key", required=False)
@click.pass_context
def config_store(ctx, key) -> None:
    """Read the persistent store (ref breeze config store): pass
    nothing for the full inventory (daemon drain/override/policy state
    + ctrl: operator keys), or a key exactly as the inventory prints
    it. Operator (ctrl:) keys print their FULL value; daemon keys show
    size + a text preview (their values are binary serde)."""
    dump = _call(ctx, "ctrl.store.dump")
    if key:
        if key not in dump:
            raise click.ClickException(
                f"{key!r} not in the store (have: {sorted(dump)})"
            )
        entry = dict(dump[key])
        if key.startswith("ctrl:"):
            entry["value"] = _call(
                ctx, "ctrl.store.get", {"key": key[len("ctrl:"):]}
            )
        _print({key: entry})
        return
    _print(dump)


@config_group.command("set")
@click.argument("key")
@click.argument("value")
@click.pass_context
def config_set(ctx, key, value) -> None:
    """Write a persistent-store key (ref setConfigKey)."""
    _print(_call(ctx, "ctrl.store.set", {"key": key, "value": value}))


@config_group.command("erase")
@click.argument("key")
@click.pass_context
def config_erase(ctx, key) -> None:
    """Erase a persistent-store key (ref eraseConfigKey)."""
    _print(_call(ctx, "ctrl.store.erase", {"key": key}))


# the historical spellings stay as aliases of the same commands
openr.add_command(config_show, name="config")
openr.add_command(config_dryrun, name="dryrun-config")


# -- monitor ----------------------------------------------------------------

@cli.group()
def monitor() -> None:
    """Counters and stats."""


@monitor.command()
@click.option("--prefix", default="")
@click.option("--json", "as_json", is_flag=True,
              help="raw JSON instead of the aligned table")
@click.pass_context
def counters(ctx, prefix, as_json) -> None:
    """Counter dump: aligned name/value table by default, --json for
    the raw machine-readable map."""
    data = _call(ctx, "monitor.counters", {"prefix": prefix})
    if as_json:
        _print(data)
        return
    width = max((len(k) for k in data), default=0)
    for key in sorted(data):
        v = data[key]
        sv = str(int(v)) if float(v).is_integer() else f"{v:.3f}"
        click.echo(f"{key:<{width}}  {sv}")


@monitor.command("logs")
@click.option("--category", default=None,
              help="filter by event name, prefix, or sample category")
@click.pass_context
def event_logs(ctx, category) -> None:
    """Sampled event logs (ref getEventLogs)."""
    _print(_call(ctx, "ctrl.monitor.logs", {"category": category}))


@monitor.command("fleet")
@click.pass_context
def monitor_fleet(ctx) -> None:
    """Fleet health: every node's monitor:health:<node> advertisement
    as seen from this node's KvStore — watchdog state, worst queue
    depth, convergence p99, HBM in use, sentinel anomalies."""
    _print(_call(ctx, "ctrl.monitor.fleet"))


@monitor.command("slo")
@click.pass_context
def monitor_slo(ctx) -> None:
    """SLO burn-rate report: per-SLO state (ok/fast_burn/
    sustained_burn), current value vs threshold, fast/slow-window
    breach fractions, and alert counts."""
    _print(_call(ctx, "ctrl.monitor.slo"))


@monitor.command("boot")
@click.pass_context
def monitor_boot(ctx) -> None:
    """Boot-to-first-RIB lifecycle: per-phase wall times (config load,
    device init, jit-cache attach, prewarm, initial sync, first solve
    with its compile/device/mat split, first RIB delta, first FIB
    program) and the boot.first_rib_ms headline. The cold-start triage
    entry point (docs/Operations.md)."""
    _print(_call(ctx, "ctrl.monitor.boot"))


@monitor.command("dump")
@click.option("--reason", default="manual", help="trigger attribution "
              "recorded in the bundle")
@click.pass_context
def monitor_dump(ctx, reason) -> None:
    """Freeze the flight recorder NOW: writes a post-mortem bundle
    (bundle.json + Chrome trace.json) and prints its path. Bypasses
    the automatic-trigger rate limit."""
    _print(_call(ctx, "ctrl.monitor.dump", {"reason": reason}))


@monitor.command("bundles")
@click.pass_context
def monitor_bundles(ctx) -> None:
    """List flight-recorder bundles: what survives on disk after
    retention (monitor_config.flight_recorder_keep newest) plus the
    in-memory record ring, with each bundle's trigger reason and
    whether it carries a replayable `inputs` annex."""
    _print(_call(ctx, "ctrl.monitor.bundles"))


@monitor.command("record")
@click.option("--reason", default="record", help="trigger attribution "
              "recorded in the bundle")
@click.pass_context
def monitor_record(ctx, reason) -> None:
    """Freeze a REPLAYABLE bundle: asks the input black-box recorder
    to re-anchor its LSDB snapshot at the next solve, then writes a
    bundle carrying the `inputs` annex (snapshot + event ring + digest
    ledger). Feed the printed path to `python -m tools.replay`."""
    _print(_call(ctx, "ctrl.monitor.record", {"reason": reason}))


@monitor.command("statistics")
@click.option("--prefix", default="")
@click.pass_context
def statistics(ctx, prefix) -> None:
    """Multi-window stat view (ref breeze monitor statistics):
    count/sum/avg/max/p50/p95/p99 over 60/600/3600 s per recorded
    stat."""
    _print(_call(ctx, "monitor.statistics", {"prefix": prefix}))


@monitor.command("spans")
@click.option("--limit", default=20, help="most-recent traces to show")
@click.option("--trace-id", default=None, type=int,
              help="show one trace by id")
@click.option("--active", is_flag=True, help="include unclosed traces")
@click.pass_context
def monitor_spans(ctx, limit, trace_id, active) -> None:
    """Convergence traces: span trees of recent topology events
    (kvstore receipt -> spf -> rib materialize -> fib -> platform)."""
    _print(_call(ctx, "monitor.traces", {
        "limit": limit, "trace_id": trace_id, "include_active": active,
    }))


@monitor.command("trace-export")
@click.option("--limit", default=20, help="most-recent traces to export")
@click.option("--trace-id", default=None, type=int,
              help="export one trace by id")
@click.option("--out", default="", help="write to a file instead of stdout")
@click.pass_context
def monitor_trace_export(ctx, limit, trace_id, out) -> None:
    """Export traces as Chrome trace-event JSON — open the output in
    chrome://tracing or ui.perfetto.dev."""
    doc = _call(ctx, "monitor.traces.export_chrome",
                {"limit": limit, "trace_id": trace_id})
    if out:
        with open(out, "w") as f:
            json.dump(doc, f)
        click.echo(f"wrote {len(doc.get('traceEvents', []))} events to {out}")
    else:
        click.echo(json.dumps(doc))


@monitor.command("heap-profile")
@click.option("--start", "action", flag_value="start",
              help="begin tracing allocations")
@click.option("--dump", "action", flag_value="dump", default=True,
              help="show top allocation sites (default)")
@click.option("--stop", is_flag=True, help="stop tracing after dump")
@click.option("--top", default=25)
@click.pass_context
def heap_profile(ctx, action, stop, top) -> None:
    """Heap profiling (ref MonitorBase::dumpHeapProfile; tracemalloc)."""
    if action == "start":
        if stop:
            raise click.UsageError(
                "--start and --stop are exclusive; dump with --stop to "
                "end a trace"
            )
        _print(_call(ctx, "monitor.heap_profile.start"))
    else:
        _print(_call(ctx, "monitor.heap_profile.dump",
                     {"top": top, "stop": stop}))


@monitor.command("crashes")
@click.pass_context
def monitor_crashes(ctx) -> None:
    """Recent task crashes (runtime crash ring), newest first — the
    forensic twin of the runtime.task_crash.* counters."""
    _print(_call(ctx, "ctrl.monitor.crashes"))


# -- fault injection --------------------------------------------------------

@cli.group()
def fault() -> None:
    """Deterministic fault-injection drills (runtime/faults.py)."""


@fault.command("inject")
@click.argument("site")
@click.option("--probability", default=0.0, type=float,
              help="fire with this probability per check (0..1)")
@click.option("--every-nth", default=0, type=int,
              help="fire deterministically every Nth check")
@click.option("--one-shot", is_flag=True, help="fire once, then disarm")
@click.option("--window", "window_s", default=0.0, type=float,
              help="auto-disarm after this many seconds")
@click.option("--max-fires", default=0, type=int,
              help="disarm after this many fires (0 = unlimited)")
@click.option("--seed", default=None, type=int,
              help="override the registry seed for this site")
@click.option("--delay-ms", default=0.0, type=float,
              help="latency fault: firings SLEEP this long instead of "
              "raising (perf-regression drills)")
@click.option("--rate", default=0.0, type=float,
              help="sustained storm: fire at this target rate in "
              "events/s (token bucket — paced, not a coin flip; "
              "combine with --window for a bounded overload drill)")
@click.pass_context
def fault_inject(
    ctx, site, probability, every_nth, one_shot, window_s, max_fires,
    seed, delay_ms, rate,
) -> None:
    """Arm SITE (e.g. solver.exec, kvstore.flood, rpc.send,
    fib.program, queue.push, decision.ingest). With no schedule options
    the site fires on every check."""
    _print(_call(ctx, "ctrl.fault.inject", {
        "site": site, "probability": probability, "every_nth": every_nth,
        "one_shot": one_shot, "window_s": window_s, "max_fires": max_fires,
        "seed": seed, "delay_ms": delay_ms, "rate": rate,
    }))


@fault.command("clear")
@click.argument("site", required=False)
@click.pass_context
def fault_clear(ctx, site) -> None:
    """Disarm SITE, or every armed site when omitted."""
    _print(_call(ctx, "ctrl.fault.clear", {"site": site}))


@fault.command("list")
@click.pass_context
def fault_list(ctx) -> None:
    """Armed sites with their schedules and fire counts."""
    _print(_call(ctx, "ctrl.fault.list"))


# -- tpu --------------------------------------------------------------------

@cli.group()
def tpu() -> None:
    """Device-plane observability (profiler, kernels, HBM)."""


@tpu.command("profile")
@click.option("--seconds", default=5.0, type=float,
              help="capture duration")
@click.option("--out", "out_dir", default="",
              help="trace output directory (default: server-side tmpdir)")
@click.pass_context
def tpu_profile(ctx, seconds, out_dir) -> None:
    """Capture a JAX profiler trace on the node: starts the trace,
    waits --seconds client-side, stops it, and prints the trace
    directory (open in TensorBoard / xprof)."""
    import time as _time

    started = _call(ctx, "ctrl.tpu.profiler.start",
                    {"out_dir": out_dir or None})
    if not started.get("ok", True):
        _print(started)
        raise SystemExit(1)
    click.echo(f"capturing to {started.get('out_dir')} "
               f"for {seconds:.1f} s ...")
    _time.sleep(seconds)
    _print(_call(ctx, "ctrl.tpu.profiler.stop"))


@tpu.command("kernels")
@click.pass_context
def tpu_kernels(ctx) -> None:
    """XLA kernel cost ledger joined with achieved solver timings:
    estimated FLOPs/bytes per compiled pipeline plus achieved
    GFLOP/s and GB/s from the last solve, and the retrace sentinel's
    per-namespace unexpected-recompile counts and recent signature
    deltas (any nonzero retraces on a warm daemon is triage-worthy)."""
    _print(_call(ctx, "ctrl.tpu.kernels"))


@tpu.command("aot")
@click.option("--json", "as_json", is_flag=True,
              help="raw JSON instead of the rendered table")
@click.pass_context
def tpu_aot(ctx, as_json) -> None:
    """Persistent AOT executable cache: on-disk entries (kernel,
    signature digest, size, fingerprint, age) and this process's
    hit/miss summary. On a warm daemon `misses` should be 0 for every
    baked shape class — a nonzero count means a boot compiled something
    the cache was supposed to carry (docs/Operations.md runbook)."""
    out = _call(ctx, "ctrl.tpu.aot")
    if as_json:
        _print(out)
        return
    s = out.get("summary", {})
    if not s.get("enabled"):
        click.echo("aot cache: DISABLED")
        return
    click.echo(f"aot cache: {s.get('dir')}  (keep={s.get('keep')}, "
               f"fingerprint={s.get('fingerprint')})")
    hr = s.get("hit_rate")
    click.echo(
        f"hits={s.get('hits', 0)} misses={s.get('misses', 0)} "
        f"hit_rate={'-' if hr is None else f'{hr:.2f}'} "
        f"load_errors={s.get('load_errors', 0)} "
        f"stale={s.get('stale_fingerprint', 0)} "
        f"writes={s.get('writes', 0)} "
        f"speculative={s.get('speculative_bakes', 0)} "
        f"installs={out.get('aot_installs', 0)}"
    )
    entries = out.get("entries", [])
    if not entries:
        click.echo("(no entries on disk)")
        return
    click.echo(f"{'kernel':<44} {'size':>9} {'age':>8} "
               f"{'compile_ms':>10}  fingerprint")
    for e in sorted(entries, key=lambda e: e.get("age_s") or 0):
        if e.get("corrupt"):
            click.echo(f"{e.get('file', '?'):<44} CORRUPT")
            continue
        size_kb = (e.get("size_bytes") or 0) / 1024
        age = e.get("age_s") or 0
        age_s = f"{age / 3600:.1f}h" if age >= 3600 else f"{age:.0f}s"
        fp = e.get("fingerprint") or "?"
        stale = " STALE" if e.get("stale") else ""
        click.echo(
            f"{(e.get('kernel') or '?')[:44]:<44} {size_kb:>8.1f}K "
            f"{age_s:>8} {(e.get('compile_ms') or 0):>10.1f}  {fp}{stale}"
        )


@tpu.command("devices")
@click.pass_context
def tpu_devices(ctx) -> None:
    """Per-device HBM gauges + live-buffer census."""
    _print(_call(ctx, "ctrl.tpu.devices"))


# -- tech-support -----------------------------------------------------------

@cli.command("tech-support")
@click.pass_context
def tech_support(ctx) -> None:
    """Dump everything (ref breeze tech-support)."""
    for title, method, params in [
        ("VERSION", "openr.version", {}),
        ("INITIALIZATION", "openr.initialization_events", {}),
        ("RUNNING CONFIG", "ctrl.config.get", {}),
        ("DRAIN STATE", "openr.drain_state", {}),
        ("KVSTORE PEERS", "ctrl.kvstore.peers", {}),
        ("FLOOD TOPOLOGY", "ctrl.kvstore.flood_topo", {}),
        ("KVSTORE DUMP", "ctrl.kvstore.dump", {}),
        ("ADJACENCIES", "ctrl.decision.adj_dbs", {}),
        ("COMPUTED ROUTES", "ctrl.decision.routes", {}),
        ("PROGRAMMED ROUTES", "ctrl.fib.routes", {}),
        ("LINKS", "ctrl.lm.links", {}),
        ("NEIGHBORS", "ctrl.spark.neighbors", {}),
        ("ADVERTISED PREFIXES", "ctrl.prefixmgr.advertised", {}),
        ("DECISION VALIDATE", "ctrl.decision.validate", {}),
        ("FIB VALIDATE", "ctrl.fib.validate", {}),
        ("SUBSCRIBERS", "ctrl.subscriber_info", {}),
        ("AOT CACHE", "ctrl.tpu.aot", {}),
        ("COUNTERS", "monitor.counters", {}),
    ]:
        click.echo(f"\n==== {title} ====")
        try:
            _print(_call(ctx, method, params))
        except Exception as e:  # noqa: BLE001 — report and continue dumping
            click.echo(f"  <error: {e}>")


def main() -> None:
    cli(obj={})


if __name__ == "__main__":
    main()
