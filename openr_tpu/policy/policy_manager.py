"""Origination policy engine.

Role of the reference's openr/policy/PolicyManager.{h,cpp} +
PolicyStructs.h: the hook PrefixManager calls on every prefix it is
about to advertise. The reference wraps a closed-source policy library
behind `applyPolicy(policyName, prefixEntries)`; this is an open,
declarative engine with the same seam: named policies, ordered
statements of match (prefix-space / type / tag, AND-combined) ->
action (deny, or accept with attribute transforms), first match wins,
configurable default disposition.

Policies live in config (OpenrConfig.policies +
origination_policy naming the one PrefixManager applies), mirroring the
reference's config-sourced area/origination policies.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field, replace
from typing import Optional

from openr_tpu.types import PrefixEntry, parse_prefix


@functools.lru_cache(maxsize=65536)
def _parse_entry_prefix(prefix: str):
    """None for malformed prefixes (a bad entry from a plugin/CLI source
    must not crash the PrefixManager event loop)."""
    try:
        return parse_prefix(prefix)
    except ValueError:
        return None


@dataclass(frozen=True)
class PolicyMatch:
    """All specified conditions must hold; unspecified = wildcard.

    Cover networks are parsed ONCE at construction (policies are applied
    per advertised entry — re-parsing per evaluation is O(entries x
    covers) waste); a malformed cover raises ValueError here, which
    config validation surfaces as ConfigError at load time."""

    # prefix is matched if it falls within ANY of these networks
    prefixes: tuple[str, ...] = ()
    types: tuple[int, ...] = ()  # PrefixType values
    tags: tuple[str, ...] = ()  # ANY shared tag

    def __post_init__(self):
        object.__setattr__(
            self, "_covers", tuple(parse_prefix(p) for p in self.prefixes)
        )

    def matches(self, entry: PrefixEntry) -> bool:
        if self._covers:
            net = _parse_entry_prefix(entry.prefix)
            if net is None or not any(
                net.version == cover.version and net.subnet_of(cover)
                for cover in self._covers
            ):
                return False
        if self.types and int(entry.type) not in self.types:
            return False
        if self.tags and not (set(self.tags) & set(entry.tags)):
            return False
        return True


@dataclass(frozen=True)
class PolicyAction:
    accept: bool = True
    set_tags: tuple[str, ...] = ()  # added to the entry's tags
    set_path_preference: Optional[int] = None
    set_source_preference: Optional[int] = None
    set_prepend_label: Optional[int] = None

    def apply(self, entry: PrefixEntry) -> Optional[PrefixEntry]:
        if not self.accept:
            return None
        kw = {}
        if self.set_tags:
            kw["tags"] = tuple(sorted(set(entry.tags) | set(self.set_tags)))
        metrics = entry.metrics
        if self.set_path_preference is not None:
            metrics = replace(metrics, path_preference=self.set_path_preference)
        if self.set_source_preference is not None:
            metrics = replace(
                metrics, source_preference=self.set_source_preference
            )
        if metrics is not entry.metrics:
            kw["metrics"] = metrics
        if self.set_prepend_label is not None:
            kw["prepend_label"] = self.set_prepend_label
        return replace(entry, **kw) if kw else entry


@dataclass(frozen=True)
class PolicyStatement:
    name: str = ""
    match: PolicyMatch = field(default_factory=PolicyMatch)
    action: PolicyAction = field(default_factory=PolicyAction)


@dataclass(frozen=True)
class Policy:
    statements: tuple[PolicyStatement, ...] = ()
    default_accept: bool = True


class PolicyManager:
    """ref PolicyManager.h — applyPolicy by name."""

    def __init__(self, policies: Optional[dict[str, Policy]] = None):
        self.policies = dict(policies or {})
        # (policy, statement-or-"default") -> hit count, for introspection
        self.hit_counts: dict[tuple[str, str], int] = {}

    def apply(
        self, policy_name: str, entry: PrefixEntry
    ) -> Optional[PrefixEntry]:
        """Transformed entry, or None when denied. An unknown policy name
        accepts unchanged (a config listing a policy that was removed
        must not silently black-hole origination; the mismatch is
        surfaced by config validation)."""
        policy = self.policies.get(policy_name)
        if policy is None:
            return entry
        for i, stmt in enumerate(policy.statements):
            if stmt.match.matches(entry):
                key = (policy_name, stmt.name or f"#{i}")
                self.hit_counts[key] = self.hit_counts.get(key, 0) + 1
                return stmt.action.apply(entry)
        key = (policy_name, "default")
        self.hit_counts[key] = self.hit_counts.get(key, 0) + 1
        return entry if policy.default_accept else None

    def apply_all(
        self, policy_name: str, entries: list[PrefixEntry]
    ) -> tuple[list[PrefixEntry], list[str]]:
        """(accepted transformed entries, denied prefixes) — the
        reference's applyPolicy shape."""
        accepted: list[PrefixEntry] = []
        denied: list[str] = []
        for entry in entries:
            out = self.apply(policy_name, entry)
            if out is None:
                denied.append(entry.prefix)
            else:
                accepted.append(out)
        return accepted, denied
