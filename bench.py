"""Benchmark: full-RIB recompute across the five BASELINE.md configs —
TPU pipeline vs the CPU SpfSolver oracle (the reference publishes no
absolute numbers; the oracle re-expresses its per-root Dijkstra +
per-prefix loop, openr/decision/LinkState.cpp:836 + SpfSolver.cpp:460).

Prints exactly ONE JSON line on stdout:
  {"metric": "...", "value": N, "unit": "ms", "vs_baseline": N, ...}

value        = TPU full-RIB recompute wall time on the headline config
               (100k-node LSDB), median over runs, including host
               materialization and the device round trip
vs_baseline  = CPU-oracle time / TPU time on that config

The extra "configs" key carries per-config results and a device/host
breakdown:
  sync_ms    host mirror sync (changelog delta -> device scatter)
  exec_ms    device pipeline + the one result pull (tunnel RTT included;
             measured fixed RTT is reported as rig_rtt_ms)
  mat_ms     host route materialization (delta rows only, steady state)
Progress goes to stderr. Runs on whatever device jax picks (real TPU
under the driver; CPU elsewhere).
"""

from __future__ import annotations

import json
import statistics
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _flap(states, adj_dbs, victims, round_i, area="0"):
    """Apply a metric flap on each victim node's adjacencies — BOTH link
    directions (a fiber event costs both ways), through the real update
    path (changelog -> device scatter). Large metric so traffic actually
    reroutes and routes to/through the victims change."""
    from openr_tpu.types import AdjacencyDatabase, Adjacency

    # cache the name index per adj_dbs object — holding the reference
    # itself (not its id(), which the allocator reuses across configs)
    by_name = getattr(_flap, "_index", None)
    if by_name is None or _flap._index_src is not adj_dbs:
        by_name = {db.this_node_name: db for db in adj_dbs}
        _flap._index = by_name
        _flap._index_src = adj_dbs

    metric = 50 + (round_i % 5)
    touched = {}
    victim_names = set()
    for v in victims:
        db = adj_dbs[v]
        victim_names.add(db.this_node_name)
        touched[db.this_node_name] = tuple(
            Adjacency(**{**a.__dict__, "metric": metric})
            for a in db.adjacencies
        )
    for v in victims:
        for a in adj_dbs[v].adjacencies:
            nb = a.other_node_name
            if nb in victim_names:
                continue
            ndb = by_name[nb]
            base = touched.get(nb, ndb.adjacencies)
            touched[nb] = tuple(
                Adjacency(**{**x.__dict__, "metric": metric})
                if x.other_node_name in victim_names
                else x
                for x in base
            )
    for name, adjs in touched.items():
        src = by_name[name]
        states[area].update_adjacency_database(
            AdjacencyDatabase(
                this_node_name=name,
                adjacencies=adjs,
                node_label=src.node_label,
                area=area,
            )
        )


def bench_config(name, gen, me, runs=5, flap_victims=0, cpu_baseline=True,
                 small_graph_nodes=0, tpu_kw=None, **solver_kw):
    """Run one config; returns a result dict. small_graph_nodes > 0
    exercises the "auto" backend's small-graph delegation (the solver
    routes the whole build to the CPU oracle below that node count);
    extra solver_kw (e.g. enable_lfa) go to BOTH backends, tpu_kw only
    to the device solver (multichip tier knobs have no CPU analogue)."""
    tpu_kw = dict(tpu_kw or {})
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.decision.tpu_solver import TpuSpfSolver
    from openr_tpu.models import topologies

    t0 = time.perf_counter()
    adj_dbs, prefix_dbs = gen()
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    area = next(iter(states))
    n_nodes = len(adj_dbs)
    n_links = len(states[area].all_links())
    log(
        f"[{name}] {n_nodes} nodes, {n_links} links "
        f"({time.perf_counter() - t0:.1f}s build)"
    )

    res = {"nodes": n_nodes, "links": n_links, "prefixes": len(prefix_dbs)}

    cpu_ms = None
    if cpu_baseline:
        cpu = SpfSolver(me, **solver_kw)
        t0 = time.perf_counter()
        cpu_db = cpu.build_route_db(me, states, ps)
        cpu_ms = (time.perf_counter() - t0) * 1e3
        res["cpu_ms"] = round(cpu_ms, 1)
        log(f"[{name}] cpu oracle: {cpu_ms:.1f} ms, {len(cpu_db.unicast_routes)} routes")

    tpu = TpuSpfSolver(me, small_graph_nodes=small_graph_nodes,
                   **tpu_kw, **solver_kw)
    t0 = time.perf_counter()
    tpu_db = tpu.build_route_db(me, states, ps)
    res["compile_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    log(f"[{name}] tpu first build (compile): {res['compile_ms']:.0f} ms; "
        f"plan: {tpu.last_device_stats}")
    if cpu_baseline:
        assert tpu_db.unicast_routes == cpu_db.unicast_routes, (
            f"[{name}] RIB mismatch vs oracle"
        )
        log(f"[{name}] parity vs CPU oracle OK")

    # cold full rebuild, jit warm: fresh solver state -> plan build + full
    # device pull + full host materialization (what a restarting daemon
    # pays once)
    tpu2 = TpuSpfSolver(me, small_graph_nodes=small_graph_nodes,
                    **tpu_kw, **solver_kw)
    t0 = time.perf_counter()
    cold_db = tpu2.build_route_db(me, states, ps)
    res["full_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    tm = getattr(tpu2, "last_timing", {})
    # last_timing also carries the per-area "areas" sub-dict (trace
    # folding); the breakdown only wants the scalar stage timings
    res["full_breakdown"] = {
        k: round(v, 1) for k, v in tm.items()
        if isinstance(v, (int, float))
    }
    # zero-copy program lane: device columns -> packed RouteColumnBatch
    # -> columnar dataplane sync, measured BEFORE anything forces lazy
    # entries. The decision.rib.entries_built counter standing still
    # across this lane is the proof that no per-route objects were
    # constructed on the program path (the columnar-spine headline)
    from openr_tpu.decision.column_delta import build_column_batch
    from openr_tpu.decision.columnar_rib import LazyUnicastRoutes
    from openr_tpu.runtime.counters import counters as _counters

    if isinstance(cold_db.unicast_routes, LazyUnicastRoutes):
        import asyncio as _asyncio

        from openr_tpu.platform.fib_handler import MemoryDataplane

        eb0 = int(_counters.get_counter("decision.rib.entries_built") or 0)
        t0 = time.perf_counter()
        batch = build_column_batch(cold_db.unicast_routes)
        if batch is not None:
            dp = MemoryDataplane()
            _asyncio.run(dp.sync_unicast_columns(batch))
            res["cold_program_ms"] = round(
                (time.perf_counter() - t0) * 1e3, 1
            )
            res["cold_program_routes"] = len(dp.unicast)
            # 0 == the whole program path stayed in packed-array land
            res["cold_program_entries_built"] = (
                int(_counters.get_counter("decision.rib.entries_built") or 0)
                - eb0
            )
            del dp, batch
    # consumption boundary: force every lazy entry in one bulk pass —
    # what Fib's first full sync pays on top of full_ms. The columnar
    # rebuild moved eager per-entry construction out of full_ms into
    # this bounded, vectorized pass (ISSUE 1 target: >=2x under the
    # eager seed's mat_ms)
    t0 = time.perf_counter()
    n_cold = len(dict(cold_db.unicast_routes))
    res["cold_consume_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    # overlap efficiency: sum of per-area sync/exec/mat stage time vs
    # the pipeline's wall clock. >1.0 means the worker thread's
    # device-pull + column scatter genuinely ran under the main
    # thread's next-area sync / host-route work
    wall = tm.get("pipeline_wall_ms")
    stages = tm.get("pipeline_stages_ms")
    if wall and stages:
        res["overlap_efficiency"] = round(stages / wall, 2)
    log(f"[{name}] tpu cold full rebuild (warm jit): {res['full_ms']:.0f} ms "
        f"{res['full_breakdown']} "
        f"program({res.get('cold_program_routes')} routes): "
        f"{res.get('cold_program_ms')} ms "
        f"entries_built {res.get('cold_program_entries_built')} "
        f"consume({n_cold} routes): "
        f"{res['cold_consume_ms']:.0f} ms "
        f"overlap: {res.get('overlap_efficiency')}")
    del tpu2, cold_db

    # steady-state full recompute through real churn (changelog path)
    victims = list(range(1, (flap_victims or 1) + 1))
    from openr_tpu.runtime.counters import counters as _counters

    _XLA_KEYS = ("factory_hits", "factory_misses", "executable_evictions")
    xla0 = {
        k: int(_counters.get_counter(f"xla_cache.{k}") or 0)
        for k in _XLA_KEYS
    }
    retrace0 = sum(
        _counters.get_counters("xla_cache.retraces.").values()
    )
    from openr_tpu.runtime.latency_budget import latency_budget

    samples, phases, budget_rows = [], {}, []
    dispatch = getattr(tpu, "dispatch_route_db", None)
    for i in range(runs):
        _flap(states, adj_dbs, victims, i, area)
        t0 = time.perf_counter()
        # per-solve latency budget: drive the explicit dispatch/collect
        # split so the churn loop emits per-component columns (no
        # program/ack stage in this lane — the storm lane covers those)
        bud = latency_budget.begin(("churn", name, i))
        if dispatch is not None:
            pending = dispatch(me, states, ps)
            if bud is not None:
                bud.advance("host_sync")
            tpu.collect_route_db(pending)
            tm_i = getattr(tpu, "last_timing", {}) or {}
            if bud is not None:
                bud.advance_split(
                    {
                        "device_exec": tm_i.get("exec_ms"),
                        "payload_apply": tm_i.get("mat_ms"),
                    },
                    primary="collect_block",
                )
        else:
            tpu.build_route_db(me, states, ps)
            if bud is not None:
                bud.advance("device_exec")
        budget_rows.append(latency_budget.close(bud))
        samples.append((time.perf_counter() - t0) * 1e3)
        for k, v in getattr(tpu, "last_timing", {}).items():
            if isinstance(v, (int, float)):
                phases.setdefault(k, []).append(v)
    tpu_ms = statistics.median(samples)
    res["tpu_ms"] = round(tpu_ms, 1)
    # steady-state convergence latency distribution (same interpolation
    # as the runtime stat fabric, so BENCH and monitor.statistics agree)
    from openr_tpu.runtime.counters import _percentile

    sv = sorted(samples)
    res["convergence_ms"] = {
        "p50": round(_percentile(sv, 50.0), 1),
        "p99": round(_percentile(sv, 99.0), 1),
    }
    for k in ("sync_ms", "exec_ms", "mat_ms"):
        phases.setdefault(k, [])
    res["stage_percentiles"] = {}
    for k, vals in phases.items():
        # a phase absent from a run contributed 0 to it — backfill so
        # medians aren't computed over only the runs where it fired
        vals = vals + [0] * (runs - len(vals))
        res[k] = round(statistics.median(vals), 1)
        pv = sorted(vals)
        res["stage_percentiles"][k] = {
            "p50": round(_percentile(pv, 50.0), 1),
            "p99": round(_percentile(pv, 99.0), 1),
        }
    # uniform across fabric sizes: 0 when the delta pull had no changed
    # rows (or the config delegated to the CPU oracle), never null
    res["changed_rows"] = int(tpu.last_device_stats.get("changed_rows") or 0)
    # per-component latency-budget columns + conservation (ISSUE 17)
    res.update(_budget_summary(budget_rows))
    # peak HBM across devices at end of the churn loop — None on backends
    # (cpu) that don't expose memory_stats()
    from openr_tpu.runtime.device_stats import peak_hbm_mb

    peak_mb, backend = peak_hbm_mb()
    res["backend"] = backend
    if peak_mb is not None:
        res["peak_hbm_mb"] = round(peak_mb, 1)
    # device-only: chained dispatches, one blocking sync amortized —
    # what the chip does per solve, with the rig's fixed transfer RTT
    # (rig_rtt_ms) excluded
    dev_ms = tpu.device_compute_ms()
    if dev_ms is not None:
        res["device_ms"] = round(dev_ms, 1)
        # the exec_ms <-> device_ms gap: dispatch overhead + the one
        # result pull (rig RTT) — the quantity the async dispatch /
        # delta-resident sync work drives down. Per-solve bytes_uploaded
        # rides last_timing into the phase medians above.
        res["exec_overhead_ms"] = round(res["exec_ms"] - dev_ms, 1)
    if cpu_ms:
        res["speedup"] = round(cpu_ms / tpu_ms, 2)
        if dev_ms:
            res["device_speedup"] = round(cpu_ms / dev_ms, 2)
    # multichip capacity tier: whether the steady-state solves ran
    # through the sharded path, the mesh factorization they used, and
    # the per-shard completion timings (a straggler device is one
    # outlier entry in shard_ms)
    mc = getattr(tpu, "last_timing", {}).get("multichip")
    res["multichip_engaged"] = bool(mc)
    if mc:
        res["multichip"] = mc
    else:
        # the phase-median loop above folds last_timing's bool flags in
        # as 0s; an off tier reports only multichip_engaged=False
        res.pop("multichip", None)
    # executable-cache health over the churn loop (deltas vs the loop
    # start, so other configs/tests in the process don't pollute the
    # reading): a steady state that misses (recompiles) or evicts here
    # is a capacity-class leak
    res["xla_cache"] = {
        k: int(_counters.get_counter(f"xla_cache.{k}") or 0) - xla0[k]
        for k in _XLA_KEYS
    }
    # unexpected recompiles over the churn loop (retrace sentinel,
    # summed across namespaces). A warm steady state must report 0 —
    # the smoke test gates on it; any nonzero means a trace-level
    # cache-class fork that the factory key did not capture
    res["xla_cache"]["retraces"] = int(
        sum(_counters.get_counters("xla_cache.retraces.").values())
        - retrace0
    )
    # async dispatch queue depth gauge (0 unless a Decision actor with
    # async_dispatch ran in this process; reported so daemon-embedded
    # bench runs surface backlog)
    res["dispatch_queue_depth"] = int(
        _counters.get_counter("decision.dispatch.depth") or 0
    )
    # flight-recorder overhead (runtime/monitor.py FlightRecorder): the
    # always-on cost is one raw-counter ring append per monitor tick —
    # nothing hooks the solve path. Price a tick against the measured
    # churn iteration: even ticking once PER SOLVE (far above the 1 Hz
    # production cadence) must fit the ≤1% budget the smoke test pins.
    from openr_tpu.config import MonitorConfig
    from openr_tpu.runtime.monitor import FlightRecorder

    _recorder = FlightRecorder(me, MonitorConfig())
    _FR_TICKS = 200
    t0 = time.perf_counter()
    for _ in range(_FR_TICKS):
        _recorder.record_tick()
    fr_tick_ms = (time.perf_counter() - t0) * 1e3 / _FR_TICKS
    res["flightrec_tick_ms"] = round(fr_tick_ms, 4)
    res["flightrec_overhead_pct"] = round(
        100.0 * fr_tick_ms / max(tpu_ms, 1e-6), 3
    )
    log(f"[{name}] tpu recompute: {[f'{s:.0f}' for s in samples]} ms "
        f"(sync {res['sync_ms']} / exec {res['exec_ms']} / mat {res['mat_ms']} "
        f"/ device-only {res.get('device_ms')} "
        f"/ uploaded {res.get('bytes_uploaded')} B "
        f"/ xla {res['xla_cache']})")

    # incremental churn lane: same fabric, single-victim metric flaps
    # against a solver with the seed-from-previous path enabled, so each
    # config reports incr_device_ms / incr_changed_rows next to its
    # full-solve numbers. Skipped when the config delegated to the CPU
    # oracle (no device path to make incremental). The incr executable
    # cache deltas ride along: a steady flap sequence reuses ONE dirty
    # bucket, so incr_executable_evictions staying 0 is the health
    # signal the smoke test pins.
    if res.get("device_ms") is not None:
        _INCR_KEYS = (
            "incr_factory_hits", "incr_factory_misses",
            "incr_executable_evictions",
        )
        ix0 = {
            k: int(_counters.get_counter(f"xla_cache.{k}") or 0)
            for k in _INCR_KEYS
        }
        tpu_i = TpuSpfSolver(
            me, small_graph_nodes=small_graph_nodes,
            incremental_spf=True, **tpu_kw, **solver_kw,
        )
        tpu_i.build_route_db(me, states, ps)  # first solve: cold seed
        i_samples, engaged, cones, rows = [], 0, [], []
        for i in range(runs):
            _flap(states, adj_dbs, victims[:1], runs + i, area)
            t0 = time.perf_counter()
            tpu_i.build_route_db(me, states, ps)
            i_samples.append((time.perf_counter() - t0) * 1e3)
            st = tpu_i.last_device_stats
            if st.get("incremental") and not st.get("fell_back"):
                engaged += 1
            cones.append(int(st.get("cone") or 0))
            rows.append(int(st.get("changed_rows") or 0))
        res["incr_tpu_ms"] = round(statistics.median(i_samples), 1)
        res["incr_engaged"] = engaged
        res["incr_runs"] = runs
        res["incr_cone"] = max(cones) if cones else 0
        res["incr_changed_rows"] = max(rows) if rows else 0
        res["incr_xla_cache"] = {
            k: int(_counters.get_counter(f"xla_cache.{k}") or 0) - ix0[k]
            for k in _INCR_KEYS
        }
        i_dev = tpu_i.incr_device_compute_ms()
        if i_dev is not None:
            res["incr_device_ms"] = round(i_dev, 2)
        log(f"[{name}] tpu incremental churn: "
            f"{[f'{s:.0f}' for s in i_samples]} ms "
            f"(engaged {engaged}/{runs} / device-only "
            f"{res.get('incr_device_ms')} / cone {res['incr_cone']} "
            f"/ changed {res['incr_changed_rows']} "
            f"/ xla {res['incr_xla_cache']})")
        del tpu_i

    # kernel A/B lane: sync vs bucketed Δ-stepping (ops/relax.py) over
    # the SAME flap sequence (round indices match, so each lane sees
    # identical per-run graphs). Records device-only time, executed
    # relaxation rounds, bucket epochs, and the multichip halo-exchange
    # count — the round/halo delta is the bucketed kernel's whole claim.
    if res.get("device_ms") is not None:
        res["kernel_ab"] = {}
        for kern in ("sync", "bucketed"):
            tpu_k = TpuSpfSolver(
                me, small_graph_nodes=small_graph_nodes,
                spf_kernel=kern, **tpu_kw, **solver_kw,
            )
            tpu_k.build_route_db(me, states, ps)  # warm jit
            k_samples, k_rounds, k_epochs, k_halo, k_engaged = (
                [], [], [], [], 0
            )
            for i in range(runs):
                _flap(states, adj_dbs, victims, 2 * runs + i, area)
                t0 = time.perf_counter()
                tpu_k.build_route_db(me, states, ps)
                k_samples.append((time.perf_counter() - t0) * 1e3)
                tm_k = getattr(tpu_k, "last_timing", {})
                k_rounds.append(int(tm_k.get("rounds") or 0))
                k_epochs.append(int(tm_k.get("bucket_epochs") or 0))
                k_halo.append(int(tm_k.get("halo_exchanges") or 0))
                if tm_k.get("spf_kernel") == "bucketed":
                    k_engaged += 1
            lane = {
                "tpu_ms": round(statistics.median(k_samples), 1),
                "rounds": max(k_rounds) if k_rounds else 0,
                "bucket_epochs": max(k_epochs) if k_epochs else 0,
                "halo_exchanges": max(k_halo) if k_halo else 0,
                "engaged": k_engaged,
            }
            k_dev = tpu_k.device_compute_ms()
            if k_dev is not None:
                lane["device_ms"] = round(k_dev, 2)
            res["kernel_ab"][kern] = lane
            log(f"[{name}] kernel={kern}: device-only "
                f"{lane.get('device_ms')} ms / rounds {lane['rounds']} "
                f"/ epochs {lane['bucket_epochs']} "
                f"/ halo {lane['halo_exchanges']} "
                f"/ engaged {k_engaged}/{runs}")
            del tpu_k
        ab = res["kernel_ab"]
        ab["rounds_decreased"] = (
            0 < ab["bucketed"]["rounds"] < ab["sync"]["rounds"]
        )
        if ab["sync"]["halo_exchanges"]:
            ab["halo_decreased"] = (
                ab["bucketed"]["halo_exchanges"]
                < ab["sync"]["halo_exchanges"]
            )
    return res, tpu_ms, cpu_ms


def bench_whatif(name, gen, me) -> dict:
    """N-1 what-if sweep smoke (decision/whatif.py): one batched device
    dispatch sweeping every up link of the fabric. Tier-1/CPU-friendly —
    runs on whatever device jax picked, so the quick lane starts
    tracking sweep throughput (scenarios/s) and peak HBM during a sweep
    alongside the solve trajectory."""
    from openr_tpu.decision.tpu_solver import TpuSpfSolver
    from openr_tpu.decision.whatif import WhatIfEngine
    from openr_tpu.models import topologies
    from openr_tpu.runtime.counters import counters as _counters
    from openr_tpu.runtime.device_stats import peak_hbm_mb

    adj_dbs, prefix_dbs = gen()
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    tpu = TpuSpfSolver(me)
    tpu.build_route_db(me, states, ps)  # resident mirror + warm jit
    eng = WhatIfEngine(tpu)
    eng.sweep(states, ps, order=1)  # warm the sweep executable
    d0 = int(_counters.get_counter("whatif.device.batched_dispatches") or 0)
    t0 = time.perf_counter()
    out = eng.sweep(states, ps, order=1)
    sweep_ms = (time.perf_counter() - t0) * 1e3
    res = {
        "scenarios": out["scenarios"],
        "sweep_ms": round(sweep_ms, 1),
        "scenarios_per_s": round(out["scenarios"] / (sweep_ms / 1e3), 1),
        "dispatches": int(
            _counters.get_counter("whatif.device.batched_dispatches") or 0
        ) - d0,
        "partitioned": out["partitioned"],
    }
    peak_mb, backend = peak_hbm_mb()
    res["backend"] = backend
    if peak_mb is not None:
        res["peak_hbm_mb"] = round(peak_mb, 1)
    log(f"[{name}] whatif N-1 sweep: {out['scenarios']} scenarios in "
        f"{sweep_ms:.0f} ms ({res['scenarios_per_s']}/s, "
        f"{res['dispatches']} dispatch) peak_hbm {res.get('peak_hbm_mb')}")
    return res


def _budget_summary(rows: list) -> dict:
    """Flatten closed latency-budget rows (runtime/latency_budget.py)
    into per-component bench columns: budget_<comp>_{p50,p99}_ms, the
    conservation check (unattributed vs e2e), and the p50->p99 tail
    attribution (ISSUE 17 acceptance: top-2 components cover >=80% of
    the gap under flapstorm)."""
    from openr_tpu.runtime.counters import _percentile
    from openr_tpu.runtime.latency_budget import (
        BUDGET_COMPONENTS,
        tail_attribution,
    )

    rows = [r for r in rows if r]
    if not rows:
        return {}
    per = {c: [] for c in BUDGET_COMPONENTS}
    e2e, unattr = [], []
    for r in rows:
        e2e.append(r["e2e_ms"])
        unattr.append(r["unattributed_ms"])
        for c in BUDGET_COMPONENTS:
            per[c].append(r["components"].get(c, 0.0))
    out = {}
    for c in BUDGET_COMPONENTS:
        pv = sorted(per[c])
        if not pv or pv[-1] <= 0.0:
            continue  # component never engaged in this lane
        out[f"budget_{c}_p50_ms"] = round(_percentile(pv, 50.0), 3)
        out[f"budget_{c}_p99_ms"] = round(_percentile(pv, 99.0), 3)
    ev, uv = sorted(e2e), sorted(unattr)
    out["budget_e2e_p50_ms"] = round(_percentile(ev, 50.0), 3)
    out["budget_e2e_p99_ms"] = round(_percentile(ev, 99.0), 3)
    out["budget_unattributed_p99_ms"] = round(_percentile(uv, 99.0), 3)
    # conservation: total unattributed residual as a fraction of total
    # e2e across the lane's epochs (gate: < 5%)
    out["budget_unattributed_frac"] = round(
        sum(unattr) / max(sum(e2e), 1e-9), 4
    )
    out["budget_epochs"] = len(rows)
    out["budget_tail"] = tail_attribution(per, e2e)
    return out


def bench_flapstorm(name, gen, me, events=100, rate_hz=100.0,
                    flap_victims=8, small_graph_nodes=0, **solver_kw):
    """Sustained flap-storm churn lane (streaming pipeline, ISSUE 16):
    paced single-victim metric flaps at rate_hz through a
    streaming_pipeline=True solver, each epoch's RIB delta programmed
    into the mock FibService — churn-to-FIB-ack is flap-apply ->
    programming ack, per-epoch download is last_timing's
    bytes_downloaded (proportional to changed rows, not n). The closing
    idle epoch (no flap) pins the standstill property: zero changed
    rows, download still exactly one within-budget streaming payload."""
    import asyncio as _asyncio

    from openr_tpu.decision.tpu_solver import TpuSpfSolver
    from openr_tpu.fib.fib_service import MockFibService
    from openr_tpu.models import topologies
    from openr_tpu.runtime.counters import _percentile

    t0 = time.perf_counter()
    adj_dbs, prefix_dbs = gen()
    states, ps = topologies.build_states(adj_dbs, prefix_dbs)
    area = next(iter(states))
    log(f"[{name}] {len(adj_dbs)} nodes "
        f"({time.perf_counter() - t0:.1f}s build)")

    tpu = TpuSpfSolver(me, small_graph_nodes=small_graph_nodes,
                       streaming_pipeline=True, **solver_kw)
    db = tpu.build_route_db(me, states, ps)  # cold seed: full pull
    full_bytes = int(
        getattr(tpu, "last_timing", {}).get("bytes_downloaded") or 0
    )
    # warm the streamed epoch executable before pacing starts — the
    # storm measures steady-state churn, not the one-time jit compile
    _flap(states, adj_dbs, [1], 7919, area)
    db = tpu.build_route_db(me, states, ps)
    from openr_tpu.runtime.counters import counters as _counters

    # post-boot retraces over the storm (summed across namespaces, so
    # the new "stream" namespace is covered): a warm steady state must
    # report 0 — the smoke test gates on it
    retrace0 = sum(_counters.get_counters("xla_cache.retraces.").values())
    svc = MockFibService()
    victims = list(range(1, flap_victims + 1))
    interval = 1.0 / rate_hz

    from openr_tpu.decision.rib_digest import GENESIS, delta_digest, roll
    from openr_tpu.runtime.latency_budget import latency_budget
    from openr_tpu.runtime.overload import FlapDamper, OverloadController

    # overload soak instrumentation (ISSUE 19): the paced rotation runs
    # through a live controller + damper so the lane's headline proves
    # the steady-state property the smoke test gates on — bounded queue
    # depth, ZERO damping, zero shed. Damper tuned for the lane's pace:
    # an 8-victim rotation is steady churn, not a flap storm, and the
    # equilibrium figure of merit must sit well under suppress.
    octl = OverloadController(
        f"bench-{name}", queue_watermark=8,
        damper=FlapDamper(
            half_life_s=0.5, penalty=1.0, suppress_threshold=50.0,
            reuse_threshold=1.0, max_penalty=100.0,
        ),
    )

    async def _storm():
        nonlocal db
        acks, dl_bytes, rows, engaged, overflows = [], [], [], 0, 0
        budget_rows, dig_ms, depths = [], [], []
        rolling = GENESIS
        dispatch = getattr(tpu, "dispatch_route_db", None)
        start = time.perf_counter()
        for i in range(events):
            target = start + i * interval
            delay = target - time.perf_counter()
            if delay > 0:
                await _asyncio.sleep(delay)
            victim = victims[i % len(victims)]
            _flap(states, adj_dbs, [victim], i, area)
            t_ev = time.perf_counter()
            # dispatch-queue-depth proxy for this synchronous rig: how
            # many paced events are already due but not yet solved —
            # exactly what Decision's solve queue would hold. Capped at
            # the events that remain: pacing debt past the end of the
            # storm cannot queue anything
            backlog = max(0, min(events - 1, int((t_ev - start) / interval)) - i)
            octl.damper.record_change(area, f"adj:{victim}")
            octl.observe(queue_depth=backlog)
            octl.shed(backlog)
            depths.append(backlog)
            # per-event latency budget: the storm drives the explicit
            # dispatch/collect split so every churn-to-ack interval
            # decomposes into the canonical component taxonomy with the
            # conservation invariant enforced at close
            bud = latency_budget.begin(("storm", name, i))
            if dispatch is not None:
                pending = dispatch(me, states, ps)
                if bud is not None:
                    bud.advance("host_sync")
                new_db = tpu.collect_route_db(pending)
                tm_i = getattr(tpu, "last_timing", {}) or {}
                if bud is not None:
                    bud.advance_split(
                        {
                            "device_exec": tm_i.get("exec_ms"),
                            "payload_apply": tm_i.get("mat_ms"),
                        },
                        primary="collect_block",
                    )
            else:
                new_db = tpu.build_route_db(me, states, ps)
                if bud is not None:
                    bud.advance("device_exec")
            update = db.calculate_update(new_db)
            # force ONLY the changed rows (lazy column map) and program
            # them — the real Fib actor's incremental add/delete path
            changed = list(update.unicast_routes_to_update.values())
            if bud is not None:
                bud.advance("payload_apply")
            if changed:
                await svc.add_unicast_routes(0, changed)
            if update.unicast_routes_to_delete:
                await svc.delete_unicast_routes(
                    0, update.unicast_routes_to_delete
                )
            if bud is not None:
                bud.advance("program")
            budget_rows.append(
                latency_budget.close(bud, final_component="ack_rtt")
            )
            acks.append((time.perf_counter() - t_ev) * 1e3)
            # per-epoch RIB digest (ISSUE 18 replay recorder): the same
            # delta_digest the Decision actor stamps on every solve —
            # timed OUTSIDE the ack window so the headline churn-to-ack
            # keys stay comparable against pre-recorder baselines, with
            # the cost reported as its own columns (the ≤1% steady-state
            # overhead demonstration)
            t_dig = time.perf_counter()
            rolling = roll(rolling, delta_digest(update))
            dig_ms.append((time.perf_counter() - t_dig) * 1e3)
            db = new_db
            tm = getattr(tpu, "last_timing", {})
            dl_bytes.append(int(tm.get("bytes_downloaded") or 0))
            st = tm.get("stream") or {}
            if st.get("epochs"):
                engaged += 1
                overflows += int(st.get("overflows") or 0)
            rows.append(int(st.get("changed_rows") or 0))
        wall_s = time.perf_counter() - start
        return (
            acks, dl_bytes, rows, engaged, overflows, wall_s,
            budget_rows, dig_ms, depths,
        )

    (acks, dl_bytes, rows, engaged, overflows, wall_s, budget_rows,
     dig_ms, depths) = _asyncio.run(_storm())
    # idle epoch: nothing changed since the last solve — the streaming
    # payload still ships (count=0), so the download stands still at
    # exactly one within-budget payload
    tpu.build_route_db(me, states, ps)
    tm = getattr(tpu, "last_timing", {})
    idle_bytes = int(tm.get("bytes_downloaded") or 0)
    idle_rows = int((tm.get("stream") or {}).get("changed_rows") or 0)

    sa, sb = sorted(acks), sorted(dl_bytes)
    res = {
        "nodes": len(adj_dbs),
        "events": events,
        "rate_hz": rate_hz,
        "achieved_rate_hz": round(events / wall_s, 1) if wall_s else None,
        "ack_p50_ms": round(_percentile(sa, 50.0), 2),
        "ack_p99_ms": round(_percentile(sa, 99.0), 2),
        "bytes_downloaded_per_epoch": int(_percentile(sb, 50.0)),
        "bytes_downloaded_max": max(dl_bytes) if dl_bytes else 0,
        "full_plane_bytes": full_bytes,
        "idle_bytes_downloaded": idle_bytes,
        "idle_changed_rows": idle_rows,
        "changed_rows_max": max(rows) if rows else 0,
        "stream_engaged": engaged,
        "stream_overflows": overflows,
        "fib_routes": len(svc.unicast),
        "retraces": int(
            sum(_counters.get_counters("xla_cache.retraces.").values())
            - retrace0
        ),
        # overload soak headline (ISSUE 19): under the steady paced
        # rotation these must read bounded-depth / zero-damped /
        # zero-shed — the smoke test and perf_diff gate hold the line
        "dispatch_queue_depth_p99": int(
            _percentile(sorted(depths), 99.0)
        ) if depths else 0,
        "dispatch_queue_depth_max": max(depths) if depths else 0,
        "damped_keys": octl.damper.damped_count(),
        "shed_epochs": octl.shed_epochs,
        "overload_state": octl.state,
    }
    if dig_ms:
        sd = sorted(dig_ms)
        res["rib_digest_p50_ms"] = round(_percentile(sd, 50.0), 3)
        res["rib_digest_p99_ms"] = round(_percentile(sd, 99.0), 3)
        # steady-state recorder overhead: digest time as a fraction of
        # the churn-to-ack interval it would ride inside in production
        res["rib_digest_overhead_pct"] = round(
            100.0 * sum(dig_ms) / max(sum(acks), 1e-9), 2
        )
    res.update(_budget_summary(budget_rows))
    log(f"[{name}] flapstorm: ack p50 {res['ack_p50_ms']} / p99 "
        f"{res['ack_p99_ms']} ms at {res['achieved_rate_hz']} ev/s "
        f"(asked {rate_hz}) / dl {res['bytes_downloaded_per_epoch']} B "
        f"per epoch (full {full_bytes} B) / idle {idle_bytes} B "
        f"/ engaged {engaged}/{events}")
    if dig_ms:
        log(f"[{name}] rib digest: p50 {res['rib_digest_p50_ms']} / p99 "
            f"{res['rib_digest_p99_ms']} ms "
            f"({res['rib_digest_overhead_pct']}% of churn-to-ack)")
    tail = (res.get("budget_tail") or {}).get("ranked") or []
    log(f"[{name}] budget: e2e p99 {res.get('budget_e2e_p99_ms')} ms, "
        f"unattributed frac {res.get('budget_unattributed_frac')}, "
        f"tail owners "
        f"{[(t['component'], t['gap_ms']) for t in tail[:2]]}")
    log(f"[{name}] overload soak: state {res['overload_state']} / "
        f"queue depth p99 {res['dispatch_queue_depth_p99']} "
        f"(max {res['dispatch_queue_depth_max']}) / "
        f"damped {res['damped_keys']} / shed {res['shed_epochs']}")
    return res


def _ledger_record(name: str, res: dict) -> None:
    """Append one config's headline numbers to the perf ledger — no-op
    unless $OPENR_TPU_PERF_LEDGER points somewhere, so bare bench runs
    and tests stay disk-free. tools/perf_diff.py --ledger and the
    baseline_drift SLO read these back as stored baselines."""
    from openr_tpu.runtime import perf_ledger

    lg = perf_ledger.get_ledger()
    if not lg.enabled or not isinstance(res, dict):
        return
    sig = f"n{res['nodes']}" if res.get("nodes") else "bench"
    obs = {
        k: res[k]
        for k in ("compile_ms", "full_ms", "device_ms", "tpu_ms",
                  "exec_overhead_ms", "peak_hbm_mb", "cold_program_ms",
                  "incr_device_ms", "boot_first_rib_ms",
                  "boot_first_rib_ms_warmcache", "aot_hit_rate",
                  "ack_p50_ms", "ack_p99_ms",
                  "bytes_downloaded_per_epoch")
        if isinstance(res.get(k), (int, float))
    }
    # per-component budget baselines: perf_diff --ledger and the CI gate
    # diff the breakdown, so a regression names the component that moved
    obs.update(
        {
            k: v
            for k, v in res.items()
            if k.startswith("budget_") and isinstance(v, (int, float))
        }
    )
    if obs:
        lg.record(f"solve[{name}]", obs, signature=sig, variant="default")
    for variant, kr in (res.get("kernel_ab") or {}).items():
        vo = {
            k: v for k, v in (kr or {}).items()
            if isinstance(v, (int, float))
        }
        if vo:
            lg.record(f"solve[{name}]", vo, signature=sig, variant=variant)


def bench_boot() -> dict:
    """Cold-start lane (runtime/lifecycle.py): two full node stacks on a
    MockIoMesh; measures begin() -> first programmed RIB on boot-0. An
    in-process approximation of a daemon restart — the explicit setup
    phases (config load, device init) belong to main.py, but the
    pipeline phases (initial sync, first solve, first RIB delta, first
    FIB program) and the boot.first_rib_ms headline run the real path."""
    import asyncio
    import os

    from openr_tpu.kvstore.wrapper import wait_until
    from openr_tpu.runtime.lifecycle import boot_tracer
    from openr_tpu.runtime.openr_wrapper import OpenrWrapper
    from openr_tpu.spark import MockIoMesh

    async def _run() -> dict:
        boot_tracer.reset()
        boot_tracer.begin("boot-0")
        mesh = MockIoMesh()
        kv_ports: dict[str, int] = {}
        nodes = {
            n: OpenrWrapper(n, mesh.provider(n), kv_ports)
            for n in ("boot-0", "boot-1")
        }
        mesh.connect("boot-0", "if-01", "boot-1", "if-10")
        try:
            await nodes["boot-0"].start("if-01")
            await nodes["boot-1"].start("if-10")
            nodes["boot-0"].advertise_prefix("10.99.0.1/32")
            nodes["boot-1"].advertise_prefix("10.99.0.2/32")
            await wait_until(
                lambda: boot_tracer.report().get("complete"),
                timeout_s=30.0,
            )
        finally:
            for w in nodes.values():
                await w.stop()
        return boot_tracer.report()

    report = asyncio.run(_run())
    out_dir = os.environ.get("OPENR_TPU_BOOT_TRACE_OUT", "")
    if out_dir:
        from openr_tpu.runtime.tracing import tracer as _tracer

        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "boot_report.json"), "w") as f:
            json.dump(report, f, indent=1, sort_keys=True, default=str)
        with open(os.path.join(out_dir, "boot_trace.json"), "w") as f:
            f.write(_tracer.export_chrome_json(limit=64))
    res = {
        "boot_first_rib_ms": report.get("first_rib_ms"),
        "complete": bool(report.get("complete")),
        "phases": {
            p["name"]: p["duration_ms"] for p in report.get("phases", [])
        },
    }
    log(f"[boot] first_rib {res['boot_first_rib_ms']} ms "
        f"phases {sorted(res['phases'])}")
    res.update(bench_boot_aot())
    return res


def bench_boot_aot() -> dict:
    """Cold-vs-warm AOT-cache A/B on the boot lane (ISSUE 20): the same
    two-node stack as bench_boot but with the device solver forced on,
    run twice against one AOT cache directory. Run A compiles cold and
    serializes every executable; a simulated restart then drops ALL
    in-memory compiled state (bounded jit caches, jax's own caches, the
    retrace sentinel's compile census) and run B boots against the
    populated disk cache — its prewarm is deserialize-and-install, and
    the retrace sentinel proves zero true compiles (any would page as
    aot_warm_violation). Headlines: boot_first_rib_ms_warmcache +
    aot_hit_rate (gated >= 0.9 by tools/perf_diff.py)."""
    import asyncio
    import os
    import shutil
    import tempfile

    from openr_tpu.config import DecisionConfig
    from openr_tpu.kvstore.wrapper import wait_until
    from openr_tpu.ops.xla_cache import (
        clear_all_jit_caches,
        configure_aot,
        retrace,
    )
    from openr_tpu.runtime.lifecycle import boot_tracer
    from openr_tpu.runtime.openr_wrapper import OpenrWrapper
    from openr_tpu.spark import MockIoMesh

    cache_dir = os.environ.get("OPENR_TPU_AOT_BENCH_DIR") or tempfile.mkdtemp(
        prefix="openr-aot-bench-"
    )
    cleanup = "OPENR_TPU_AOT_BENCH_DIR" not in os.environ
    aot = configure_aot(cache_dir)

    async def _one_boot() -> dict:
        boot_tracer.reset()
        boot_tracer.begin("boot-0")
        mesh = MockIoMesh()
        kv_ports: dict[str, int] = {}
        dcfg = DecisionConfig(debounce_min_ms=5, debounce_max_ms=25)
        nodes = {
            n: OpenrWrapper(
                n, mesh.provider(n), kv_ports,
                decision_config=dcfg, solver_backend="tpu",
            )
            for n in ("boot-0", "boot-1")
        }
        mesh.connect("boot-0", "if-01", "boot-1", "if-10")
        try:
            await nodes["boot-0"].start("if-01")
            await nodes["boot-1"].start("if-10")
            nodes["boot-0"].advertise_prefix("10.99.0.1/32")
            nodes["boot-1"].advertise_prefix("10.99.0.2/32")
            await wait_until(
                lambda: boot_tracer.report().get("complete"),
                timeout_s=60.0,
            )
        finally:
            for w in nodes.values():
                await w.stop()
        return boot_tracer.report()

    try:
        cold = asyncio.run(_one_boot())

        # simulated daemon restart: the disk cache survives, nothing
        # in-memory does — exactly what a real process restart drops
        import jax

        clear_all_jit_caches()
        jax.clear_caches()
        retrace.reset()
        aot.reset_stats()
        preload = aot.preload()

        warm = asyncio.run(_one_boot())
        summary = aot.summary()
        scoped = retrace.snapshot()
        res = {
            "boot_first_rib_ms_coldcache": cold.get("first_rib_ms"),
            "boot_first_rib_ms_warmcache": warm.get("first_rib_ms"),
            "aot_hit_rate": summary.get("hit_rate"),
            "aot_hits": summary.get("hits"),
            "aot_misses": summary.get("misses"),
            "aot_entries": summary.get("entries"),
            "aot_preloaded": preload.get("loaded"),
            "aot_warm_retraces": sum(
                (scoped.get("retraces") or {}).values()
            ),
        }
        log(
            f"[boot-aot] cold {res['boot_first_rib_ms_coldcache']} ms -> "
            f"warm {res['boot_first_rib_ms_warmcache']} ms "
            f"(hit_rate {res['aot_hit_rate']}, "
            f"{res['aot_entries']} entries)"
        )
        return res
    finally:
        configure_aot("off")
        if cleanup:
            shutil.rmtree(cache_dir, ignore_errors=True)


def _write_budget_out(configs) -> None:
    """Dump the per-lane latency-budget waterfall to
    $OPENR_TPU_BUDGET_OUT (CI uploads it as a failure artifact). The doc
    carries each lane's `budget_*` columns plus the ledger's own
    report() so a red bench lane is triageable offline — the waterfall
    names the component, not just the regressed total."""
    import os

    path = os.environ.get("OPENR_TPU_BUDGET_OUT")
    if not path:
        return
    from openr_tpu.runtime.latency_budget import latency_budget

    doc = {
        "lanes": {
            name: {
                k: v for k, v in res.items() if k.startswith("budget_")
            }
            for name, res in configs.items()
            if isinstance(res, dict)
            and any(k.startswith("budget_") for k in res)
        },
        "ledger": latency_budget.report(),
    }
    try:
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        log(f"budget waterfall: {path}")
    except OSError as exc:
        log(f"budget waterfall: write failed ({exc})")


def main() -> None:
    quick = "--quick" in sys.argv
    only = None
    for a in sys.argv[1:]:
        if a.startswith("--only="):
            only = a.split("=", 1)[1]

    import jax
    import numpy as np

    from openr_tpu.models import topologies
    from openr_tpu.ops.xla_cache import enable_compilation_cache

    cache_dir = enable_compilation_cache()
    # perf-baseline ledger: opt-in via env so bare runs stay disk-free
    import os as _env_os

    from openr_tpu.runtime import perf_ledger

    if _env_os.environ.get(perf_ledger.ENV_DIR):
        perf_ledger.configure(perf_ledger.default_dir())
        log(f"perf-ledger: {perf_ledger.get_ledger().path}")
    log(f"devices: {jax.devices()}  xla-cache: {cache_dir}")
    # measure the rig's fixed device round trip (a pull of 8 bytes):
    # everything below pays it once per recompute
    x = jax.device_put(np.zeros(2, np.int32))
    f = jax.jit(lambda a: a + 1)
    np.asarray(f(x))
    t0 = time.perf_counter()
    np.asarray(f(x))
    rtt_ms = (time.perf_counter() - t0) * 1e3
    log(f"rig fixed round-trip: {rtt_ms:.1f} ms")

    configs = {}
    headline = None

    def run(name, *args, **kw):
        if only and name != only:
            return None
        r, tpu_ms, cpu_ms = bench_config(name, *args, **kw)
        configs[name] = r
        _ledger_record(name, r)
        return r, tpu_ms, cpu_ms

    # 1: 4-node mesh — CPU parity baseline (example_openr.conf scale).
    # Runs with the "auto" backend's small-graph delegation: tiny graphs
    # solve on the CPU oracle (the device round trip alone is ~300x the
    # whole solve here).
    run("mesh4", lambda: topologies.full_mesh(4), "node-0", runs=3,
        small_graph_nodes=2816)

    # 2: 1k-node Terragraph-style mesh (street-lattice grid). Sits BELOW
    # the measured rig crossover (~2.8k nodes at this RTT), so the auto
    # backend delegates it to the oracle — asserting auto is never
    # slower than both backends at this size.
    run("tg1k", lambda: topologies.grid(32, node_labels=False), "node-16-16",
        small_graph_nodes=2816)

    # N-1 what-if sweep throughput on the 1k-node mesh: ~2k hypothetical
    # topologies against the resident graph in one batched dispatch
    if only in (None, "whatif1k"):
        configs["whatif1k"] = bench_whatif(
            "whatif1k", lambda: topologies.grid(32, node_labels=False),
            "node-16-16",
        )

    # streaming churn lane at 1k (CI-friendly size, same code path as
    # the 100k headline below): runs only when named — the quick CI
    # gate calls `--only=flapstorm_tg1k` and perf_diffs the committed
    # BENCH_FLAPSTORM baseline
    if only == "flapstorm_tg1k":
        configs["flapstorm_tg1k"] = bench_flapstorm(
            "flapstorm_tg1k",
            lambda: topologies.grid(32, node_labels=False),
            "node-16-16", events=60, rate_hz=100.0,
        )
        _ledger_record("flapstorm_tg1k", configs["flapstorm_tg1k"])

    # cold-start lane: boot-to-first-RIB through the full node stack
    # (skipped in --only runs that name another config)
    if only in (None, "boot"):
        configs["boot"] = bench_boot()
        _ledger_record("boot", configs["boot"])

    if quick:
        if not configs:
            sys.exit(f"--only={only} matched no config")
        _write_budget_out(configs)
        name = "tg1k" if "tg1k" in configs else next(iter(configs))
        out = configs[name]
        print(json.dumps({
            "metric": f"full_rib_recompute_{name}_ms",
            "value": out.get(
                "tpu_ms",
                out.get(
                    "sweep_ms",
                    out.get("boot_first_rib_ms", out.get("ack_p99_ms")),
                ),
            ),
            "unit": "ms",
            "vs_baseline": out.get("speedup", 1.0),
            "rig_rtt_ms": round(rtt_ms, 1),
            "boot_first_rib_ms": configs.get("boot", {}).get(
                "boot_first_rib_ms"
            ),
            "boot_first_rib_ms_warmcache": configs.get("boot", {}).get(
                "boot_first_rib_ms_warmcache"
            ),
            "aot_hit_rate": configs.get("boot", {}).get("aot_hit_rate"),
            "configs": configs,
        }))
        return

    # 3: 10k-node fat-tree fabric, ECMP + LFA backup next-hops (the CPU
    # oracle pays one extra Dijkstra per neighbor; the device derives
    # alternates from distance fields it already holds)
    run(
        "fabric10k",
        lambda: topologies.fabric(pods=96, planes=8, ssws_per_plane=36,
                                  rsws_per_pod=64),
        "pod000-rsw00",
        enable_lfa=True,
    )

    # 4: 50k-node WAN with a segment-routed KSP2 subset (every 768th
    # node's loopback is SR_MPLS + KSP2_ED_ECMP -> 64 destinations whose
    # per-destination second-pass SPFs batch on device, ops/ksp2.py)
    run(
        "wan50k",
        lambda: topologies.wan(regions=48, region_side=32, ksp2_every=768),
        "r00-n08-08",
    )

    # 5: 100k-node synthetic LSDB (grid, 400k directed adjacencies) +
    #    1k-link flap burst
    r5 = run(
        "lsdb100k",
        lambda: topologies.grid(316, node_labels=False),
        "node-158-158",
        runs=3,
        flap_victims=250,  # 250 nodes x ~4 links = ~1k directed flaps
    )
    if r5 is not None:
        headline = ("full_rib_recompute_100k_ms", r5[1], r5[2])

    # 5a: sustained flap storm at the 100k headline scale — the
    # streaming pipeline's churn-to-FIB-ack distribution and per-epoch
    # download (ISSUE 16 acceptance: p99 < 25 ms on the TPU rig, bytes
    # proportional to changed rows)
    if only in (None, "flapstorm100k"):
        configs["flapstorm100k"] = bench_flapstorm(
            "flapstorm100k",
            lambda: topologies.grid(316, node_labels=False),
            "node-158-158", events=200, rate_hz=100.0,
        )
        _ledger_record("flapstorm100k", configs["flapstorm100k"])

    # 5b: the SAME 100k LSDB forced through the multichip capacity tier
    # (n_cap 131072 sits exactly AT the default threshold, so halving it
    # engages the sharded path) — the single-chip vs multichip device_ms
    # side-by-side is the tier's go/no-go number at this scale
    if len(jax.devices()) > 1:
        run(
            "lsdb100k_mc",
            lambda: topologies.grid(316, node_labels=False),
            "node-158-158",
            runs=3,
            flap_victims=250,
            tpu_kw={"multichip_n_cap_threshold": 65536},
        )

    # 6: 1M-node synthetic LSDB (grid 1000x1000, ~4M directed
    # adjacencies) through the production Decision path — the multichip
    # tier engages at the default threshold. Host topology construction
    # alone holds ~5M python objects, so the lane is memory-gated: on a
    # short box it reports a skip instead of an OOM kill. The CPU-oracle
    # parity assert (~minutes of host Dijkstra) is opt-in via
    # OPENR_TPU_BENCH_1M_ORACLE=1.
    import os as _os

    mem_gb = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    mem_gb = int(line.split()[1]) / 1e6
                    break
    except OSError:
        pass
    if only in (None, "lsdb1m") and (mem_gb is None or mem_gb >= 12.0):
        run(
            "lsdb1m",
            lambda: topologies.grid(1000, node_labels=False),
            "node-500-500",
            runs=1,
            flap_victims=100,
            cpu_baseline=_os.environ.get(
                "OPENR_TPU_BENCH_1M_ORACLE", ""
            ) == "1",
        )
    elif only in (None, "lsdb1m"):
        configs["lsdb1m"] = {
            "skipped": f"MemAvailable {mem_gb:.1f} GB < 12 GB"
        }
        log(f"[lsdb1m] skipped: MemAvailable {mem_gb:.1f} GB < 12 GB")

    if headline is None:
        last = next(
            (n for n in reversed(configs) if "tpu_ms" in configs[n]),
            None,
        )
        if last is None:
            sys.exit("no config produced a headline timing")
        headline = (
            f"full_rib_recompute_{last}_ms",
            configs[last]["tpu_ms"],
            configs[last].get("cpu_ms"),
        )
    metric, tpu_ms, cpu_ms = headline
    _write_budget_out(configs)
    dev = configs.get("lsdb100k", {}).get("device_ms")
    print(json.dumps({
        "metric": metric,
        "value": round(tpu_ms, 2),
        "unit": "ms",
        "vs_baseline": round((cpu_ms or tpu_ms) / tpu_ms, 2),
        "rig_rtt_ms": round(rtt_ms, 1),
        "device_ms_100k": dev,
        "incr_device_ms_100k": configs.get("lsdb100k", {}).get(
            "incr_device_ms"
        ),
        # bucketed Δ-stepping headlines: single-chip device-only time at
        # 100k under each kernel, and the 1M multichip halo-exchange
        # count (one pmin per bucket EPOCH under bucketed vs one per
        # relaxation round under sync)
        "device_ms_100k_bucketed": configs.get("lsdb100k", {}).get(
            "kernel_ab", {}
        ).get("bucketed", {}).get("device_ms"),
        "device_ms_100k_sync": configs.get("lsdb100k", {}).get(
            "kernel_ab", {}
        ).get("sync", {}).get("device_ms"),
        "mc_halo_exchanges_1m": configs.get("lsdb1m", {}).get(
            "kernel_ab", {}
        ).get("bucketed", {}).get("halo_exchanges"),
        "mc_halo_exchanges_1m_sync": configs.get("lsdb1m", {}).get(
            "kernel_ab", {}
        ).get("sync", {}).get("halo_exchanges"),
        # the 100k single-chip vs multichip side-by-side: the capacity
        # tier must beat the single-chip device_ms at this scale to be
        # worth its pmin halo exchange
        "device_ms_100k_single": dev,
        "device_ms_100k_multichip": configs.get("lsdb100k_mc", {}).get(
            "device_ms"
        ),
        "multichip_engaged_100k": configs.get("lsdb100k_mc", {}).get(
            "multichip_engaged"
        ),
        "multichip_engaged_1m": configs.get("lsdb1m", {}).get(
            "multichip_engaged"
        ),
        # columnar-spine headline: cold host materialization + the
        # zero-copy program/consume lanes at 100k and 1M (program must
        # report entries_built == 0 — no per-route objects on the path)
        "cold_mat_ms_100k": configs.get("lsdb100k", {}).get(
            "full_breakdown", {}
        ).get("mat_ms"),
        "cold_program_ms_100k": configs.get("lsdb100k", {}).get(
            "cold_program_ms"
        ),
        "cold_mat_ms_1m": configs.get("lsdb1m", {}).get(
            "full_breakdown", {}
        ).get("mat_ms"),
        "cold_program_ms_1m": configs.get("lsdb1m", {}).get(
            "cold_program_ms"
        ),
        "cold_consume_ms_1m": configs.get("lsdb1m", {}).get(
            "cold_consume_ms"
        ),
        "cold_program_entries_built_1m": configs.get("lsdb1m", {}).get(
            "cold_program_entries_built"
        ),
        # The e2e value above includes one mandatory device->host result
        # round trip; on this tunneled rig that RTT (rig_rtt_ms, measured
        # with an 8-byte pull) is a fixed floor independent of problem
        # size — exec_ms is ~rtt at every scale. device_ms_100k is the
        # chip's amortized per-solve compute (chained dispatches, no
        # per-solve pull); on locally-attached TPU hosts (PCIe, ~us
        # round trips) e2e converges to device_ms + sync + mat.
        # boot lifecycle headline (runtime/lifecycle.py): cold process
        # to first programmed RIB through the full node stack — ROADMAP
        # item 1's "under 2 s" gate reads this number
        "boot_first_rib_ms": configs.get("boot", {}).get(
            "boot_first_rib_ms"
        ),
        # AOT executable cache A/B (ISSUE 20): the same boot with the
        # device solver forced on, restarted against the populated
        # serialized-executable cache — warm must sit materially below
        # cold, with >= 0.9 of lookups served from disk
        "boot_first_rib_ms_warmcache": configs.get("boot", {}).get(
            "boot_first_rib_ms_warmcache"
        ),
        "aot_hit_rate": configs.get("boot", {}).get("aot_hit_rate"),
        # streaming-churn headline (ISSUE 16): flap-apply -> FIB ack
        # p99 under a sustained 100-events/s storm at 100k, plus the
        # changed-rows-proportional per-epoch download beside the full
        # plane it replaces
        "churn_to_fib_ack_p99_ms_100k": configs.get(
            "flapstorm100k", {}
        ).get("ack_p99_ms"),
        "stream_bytes_per_epoch_100k": configs.get(
            "flapstorm100k", {}
        ).get("bytes_downloaded_per_epoch"),
        "rtt_note": "e2e = device_ms + host sync/mat + rig RTT; RTT is the tunnel's, not the design's",
        "configs": configs,
    }))


if __name__ == "__main__":
    main()
