"""ISSUE 15 device-contract sentinel tests: the retrace sentinel
(ops/xla_cache.retrace) and the opt-in transfer guard.

The sentinel tests force REAL XLA compiles (fresh `jax.jit` objects get
fresh executable caches, so warmup is deterministic) and assert the
warmup/retrace attribution rules: first compile per (namespace, kernel)
is warmup, a later one is a counted retrace carrying a signature delta,
and `forget()` resets a namespace back to warmup semantics. Arrays are
built OUTSIDE the scopes — eager ops compile their own tiny executables
and would otherwise be attributed to the scope under test.
"""

import jax
import jax.numpy as jnp
import pytest

from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.decision.tpu_solver import TpuSpfSolver
from openr_tpu.models import topologies
from openr_tpu.ops.xla_cache import retrace
from openr_tpu.runtime.counters import counters
from tests.test_tpu_solver import assert_rib_equal


def _counter(key: str) -> float:
    return counters.get_counter(key) or 0


# -- retrace sentinel unit -------------------------------------------------


class TestRetraceSentinel:
    def test_warmup_then_fork_is_one_attributed_retrace(self):
        retrace.reset()
        before = _counter("xla_cache.retraces.probe")
        f = jax.jit(lambda x: x * 2)
        a = jnp.arange(8)
        b = jnp.arange(16)

        with retrace.scope("probe", "kern", (8,)):
            f(a).block_until_ready()
        assert retrace.drain_events() == []  # first compile = warmup

        with retrace.scope("probe", "kern", (8,)):
            f(a).block_until_ready()
        assert retrace.drain_events() == []  # executable-cache hit

        # same declared signature, new array shape: a trace-level fork
        with retrace.scope("probe", "kern", (8,)):
            f(b).block_until_ready()
        events = retrace.drain_events()
        assert len(events) == 1, events
        evt = events[0]
        assert evt["namespace"] == "probe"
        assert evt["kernel"] == "kern"
        assert "trace-level fork" in evt["signature_delta"]
        assert _counter("xla_cache.retraces.probe") == before + 1

    def test_signature_change_lands_in_the_delta(self):
        retrace.reset()
        f = jax.jit(lambda x: x + 1)
        a = jnp.arange(8)
        c = jnp.arange(32)
        with retrace.scope("probe", "sig", (8,)):
            f(a).block_until_ready()
        retrace.drain_events()
        # the fork crosses a DECLARED capacity boundary: the event names
        # both signatures so triage sees which bucket edge was crossed
        with retrace.scope("probe", "sig", (32,)):
            f(c).block_until_ready()
        events = retrace.drain_events()
        assert len(events) == 1, events
        assert "(8,)" in events[0]["signature_delta"]
        assert "(32,)" in events[0]["signature_delta"]

    def test_forget_resets_namespace_to_warmup(self):
        retrace.reset()
        f = jax.jit(lambda x: x - 1)
        a = jnp.arange(8)
        b = jnp.arange(16)
        with retrace.scope("evicted", "kern", (8,)):
            f(a).block_until_ready()
        retrace.forget("evicted")  # bucket eviction dropped the exec
        with retrace.scope("evicted", "kern", (16,)):
            f(b).block_until_ready()
        assert retrace.drain_events() == []  # regrowth = warmup again

    def test_snapshot_carries_counts_census_and_recent_ring(self):
        retrace.reset()
        f = jax.jit(lambda x: x * 3)
        a = jnp.arange(8)
        b = jnp.arange(16)
        with retrace.scope("snap", "kern", (8,)):
            f(a).block_until_ready()
        with retrace.scope("snap", "kern", (8,)):
            f(b).block_until_ready()
        retrace.note_class("snap", (8,))
        retrace.note_class("snap", (16,))
        snap = retrace.snapshot()
        assert snap["retraces"] == {"snap": 1}
        assert snap["classes"] == {"snap": 2}
        # the recent ring RETAINS events drain_events() consumed — it is
        # the `breeze tpu kernels` triage surface
        retrace.drain_events()
        recent = retrace.snapshot()["recent"]
        assert [e["kernel"] for e in recent] == ["kern"]
        assert "signature_delta" in recent[0]

    def test_plain_retrace_classifies_as_retrace(self):
        # ISSUE 20: every sentinel event carries a classification so
        # triage can tell trace churn from warm-cache violations
        retrace.reset()
        f = jax.jit(lambda x: x * 9)
        a = jnp.arange(8)
        b = jnp.arange(16)
        with retrace.scope("cls", "kern", (8,)):
            f(a).block_until_ready()
        with retrace.scope("cls", "kern", (8,)):
            f(b).block_until_ready()
        [evt] = retrace.drain_events()
        assert evt["class"] == "retrace"

    def test_compile_after_aot_install_is_warm_violation(self):
        # an AOT deserialize installed the pair warm — with no compile
        # event ever firing, the FIRST real compile is not warmup: it
        # is the bug the warm-cache sentinel exists to page on
        retrace.reset()
        before = _counter("xla_cache.retraces.aotns")
        retrace.note_aot_install("aotns", "kern", (8,))
        assert retrace.snapshot()["aot_installs"] == 1

        f = jax.jit(lambda x: x * 11)
        a = jnp.arange(8)
        with retrace.scope("aotns", "kern", (8,)):
            f(a).block_until_ready()
        [evt] = retrace.drain_events()
        assert evt["class"] == "aot_warm_violation"
        assert evt["namespace"] == "aotns"
        assert _counter("xla_cache.retraces.aotns") == before + 1
        # forget() (bucket eviction) clears the install mark too: the
        # regrowth compile is warmup again, not a violation
        retrace.forget("aotns")
        assert retrace.snapshot()["aot_installs"] == 0


# -- Decision surfaces retraces as DEVICE_RETRACE LogSamples ---------------


class TestDeviceRetraceLogSample:
    def test_emit_retraces_pushes_sentinel_sample(self):
        from openr_tpu.decision.decision import Decision

        retrace.reset()
        f = jax.jit(lambda x: x * 5)
        a = jnp.arange(8)
        b = jnp.arange(16)
        with retrace.scope("emit", "kern", (8,)):
            f(a).block_until_ready()
        with retrace.scope("emit", "kern", (8,)):
            f(b).block_until_ready()

        class _Queue:
            def __init__(self):
                self.items = []

            def push(self, sample):
                self.items.append(sample)

        d = Decision.__new__(Decision)
        d.node_name = "node-0"
        d.name = "decision"
        d._log_samples = _Queue()

        class _Span:
            attributes = {}

        sp = _Span()
        d._emit_retraces(sp)
        assert sp.attributes["device_retrace"] == 1
        assert len(d._log_samples.items) == 1
        sample = d._log_samples.items[0]
        assert sample.event == "DEVICE_RETRACE"
        assert sample.node_name == "node-0"
        assert sample.values["category"] == "sentinel"
        assert sample.values["namespace"] == "emit"
        assert "signature_delta" in sample.values
        # the queue was drained — a second emit is a no-op
        d._emit_retraces(sp)
        assert len(d._log_samples.items) == 1


# -- transfer guard --------------------------------------------------------


class TestTransferGuard:
    def test_unknown_mode_rejected(self):
        with pytest.raises(ValueError, match="transfer_guard"):
            TpuSpfSolver("node-0", transfer_guard="loudly")

    def test_disallow_mode_still_converges(self):
        # the guard is a triage lever that must never break routing:
        # root tables go up via explicit device_put, and any residual
        # implicit transfer is caught, counted, and retried unguarded
        adj_dbs, pfx = topologies.grid(4, node_labels=False)
        states, ps = topologies.build_states(adj_dbs, pfx)
        me = "node-1-1"
        guarded = TpuSpfSolver(me, transfer_guard="disallow")
        oracle = SpfSolver(me)
        assert_rib_equal(
            oracle.build_route_db(me, states, ps),
            guarded.build_route_db(me, states, ps),
            "transfer_guard=disallow",
        )
