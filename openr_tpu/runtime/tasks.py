"""Fire-and-forget task spawning with strong references + error logging.

asyncio's event loop keeps only weak references to tasks, so a task spawned
with bare ensure_future can be garbage-collected mid-execution and its
exception surfaces only as "Task exception was never retrieved". Timer and
throttle callbacks route through spawn_logged() instead: the module-level
set retains the task until completion and a done-callback logs failures
with the owning component's name.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Coroutine

log = logging.getLogger("openr_tpu.runtime")

_live_tasks: set[asyncio.Task] = set()


def spawn_logged(coro: Coroutine[Any, Any, Any], name: str = "") -> asyncio.Task:
    task = asyncio.ensure_future(coro)
    if name:
        task.set_name(name)
    _live_tasks.add(task)

    def _done(t: asyncio.Task) -> None:
        _live_tasks.discard(t)
        if t.cancelled():
            return
        exc = t.exception()
        if exc is None:
            return
        # Queue closure is the quiet shutdown path, same as Actor.add_task.
        from openr_tpu.messaging import QueueClosedError

        if isinstance(exc, QueueClosedError):
            return
        log.error("task %s crashed", t.get_name(), exc_info=exc)

    task.add_done_callback(_done)
    return task
