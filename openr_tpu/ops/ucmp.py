"""Device UCMP weight propagation.

Role of the reference's `LinkState::resolveUcmpWeights`
(/root/reference/openr/decision/LinkState.cpp:913-1033): starting from
the prefix's announcers ("leaves", all equidistant from the computing
root), walk the shortest-path DAG leaf->root accumulating advertised
weights, yielding per-next-hop load-balancing weights at the root.

The reference (and our CPU oracle, link_state.resolve_ucmp_weights)
does this with a heap walk — per-node sequential along the DAG. The
device formulation observes that the walk computes a fixpoint that is
expressible as masked edge aggregations over the SSSP distance field
the solver already has:

  - DAG membership per directed edge (u -> v):
        dist[u] + w_eff(u->v) == dist[v]       (both finite)
  - reach(v): v lies on a shortest root->leaf path — the leaf set
    propagated backward one DAG level per iteration.
  - node weight w(v):
        leaf:        its advertised weight
        prefix mode: sum over DAG out-edges (v -> s, reach(s)) of w(s)
        adj mode:    sum over DAG out-edges (v -> s, reach(s)) of the
                     static link weight of (v -> s)
    (ref SP_UCMP_PREFIX_WEIGHT_PROPAGATION vs
     SP_UCMP_ADJ_WEIGHT_PROPAGATION)

Both reach and w converge in DAG-depth iterations of
`segment_sum`/`segment_max` scatter-aggregations — the same O(E)-per-
round shape as the SSSP relaxation, batch-friendly and free of the
heap's sequential dependency. The root's per-interface weights and the
gcd normalization are O(degree(root)) host work (ops consumers:
decision/tpu_solver.py installs this as the oracle's ucmp_resolver).

Weights accumulate weighted path counts, which can overflow int32 on
deep fat trees (jax's default config has no int64). A float32 shadow
of the propagation tracks magnitude — floats saturate instead of
wrapping — and flags any node weight beyond 2^30; the caller then falls
back to the host walk, whose Python ints are unbounded.
"""

from __future__ import annotations

import numpy as np

from openr_tpu.ops import relax as relax_ops
from openr_tpu.ops.edgeplan import INF32E, MAX_METRIC, natural_key
from openr_tpu.ops.xla_cache import bounded_jit_cache
from openr_tpu.runtime.counters import counters

INF_E = int(INF32E)


@bounded_jit_cache()
def _ucmp_fn(e_cap: int, n_cap: int, use_prefix_weight: bool):
    import jax
    import jax.numpy as jnp

    def f(src, dst, w_eff, adj_w, dist, leaf_mask, leaf_w):
        du, dv = dist[src], dist[dst]
        dag = (
            (w_eff < INF_E)
            & (du < INF_E)
            & (dv < INF_E)
            & (du + w_eff == dv)
        )
        zero = jnp.zeros((), jnp.int32)
        w0 = jnp.where(leaf_mask, leaf_w, zero)
        wf0 = w0.astype(jnp.float32)

        def body(state):
            _, reach, w, wf, it = state
            rv = reach[dst] & dag
            if use_prefix_weight:
                per_edge = jnp.where(rv, w[dst], zero)
                per_edge_f = jnp.where(rv, wf[dst], 0.0)
            else:
                per_edge = jnp.where(rv, adj_w, zero)
                per_edge_f = per_edge.astype(jnp.float32)
            acc = jax.ops.segment_sum(per_edge, src, num_segments=n_cap)
            new_w = jnp.where(leaf_mask, leaf_w, acc)
            new_wf = jnp.where(
                leaf_mask,
                leaf_w.astype(jnp.float32),
                jax.ops.segment_sum(per_edge_f, src, num_segments=n_cap),
            )
            hit = jax.ops.segment_max(
                rv.astype(jnp.int32), src, num_segments=n_cap
            )
            new_reach = leaf_mask | (hit > 0)
            changed = jnp.any(new_reach != reach) | jnp.any(new_w != w)
            return changed, new_reach, new_w, new_wf, it + 1

        # a true DAG converges in depth <= n_cap rounds; the bound exists
        # so that a corrupted "DAG" (a zero-weight cycle satisfies the
        # membership predicate in both directions) terminates instead of
        # oscillating forever — the non-convergence then surfaces as
        # overflow=True and the caller falls back to the exact host walk
        bound = jnp.int32(relax_ops.fixpoint_bound(n_cap))

        def cond(state):
            return state[0] & (state[4] < bound)

        changed, reach, w, wf, rounds = jax.lax.while_loop(
            cond, body, (jnp.bool_(True), leaf_mask, w0, wf0, jnp.int32(0))
        )
        # float shadow saturates instead of wrapping: any node beyond
        # 2^30 means the int32 field may have overflowed. `changed` still
        # True at exit means the bound fired before the fixpoint.
        overflow = jnp.any(wf > jnp.float32(1 << 30)) | changed
        return reach, w, overflow, rounds

    return jax.jit(f)


class UcmpEdges:
    """Directed-edge arrays for one area's LinkState, padded to a pow2
    cap, device-resident; rebuilt per topology generation (the per-link
    Python extraction is memoized by LinkState.mirror_source)."""

    def __init__(self, link_state, node_overloaded: np.ndarray,
                 n_cap: int):
        import jax

        names, index, n1i, n2i, trip, links = link_state.mirror_source(
            natural_key
        )
        m = len(links)
        e2 = m * 2
        e_cap = 1
        while e_cap < max(e2, 8):
            e_cap *= 2
        src = np.zeros(e_cap, np.int32)
        dst = np.zeros(e_cap, np.int32)
        w_eff = np.full(e_cap, INF_E, np.int32)
        adj_w = np.zeros(e_cap, np.int32)
        if m:
            src[0:e2:2] = n1i
            src[1:e2:2] = n2i
            dst[0:e2:2] = n2i
            dst[1:e2:2] = n1i
            wdir = np.empty(e2, np.int64)
            wdir[0::2] = trip[:, 0]
            wdir[1::2] = trip[:, 1]
            up2 = np.repeat(trip[:, 2].astype(bool), 2)
            # identical masking to ops/edgeplan.build_plan: a drained
            # (overloaded) source node provides no transit
            w_eff[:e2] = np.where(
                up2 & ~node_overloaded[src[:e2]],
                np.minimum(wdir, MAX_METRIC),
                INF_E,
            ).astype(np.int32)
            # static per-direction link weights; unlike metrics these are
            # never added to distances, so the INF32E clipping discipline
            # does not apply — out-of-range weights instead force the
            # exact host walk (adj_w_unsafe)
            aw = np.array(
                [
                    (l.weight_from_node(l.n1), l.weight_from_node(l.n2))
                    for l in links
                ],
                np.int64,
            )
            self.adj_w_unsafe = bool((np.abs(aw) > (1 << 30)).any())
            if not self.adj_w_unsafe:
                adj_w[0:e2:2] = aw[:, 0]
                adj_w[1:e2:2] = aw[:, 1]
            # a live zero(/negative)-metric edge makes BOTH directions
            # satisfy the DAG predicate (du + 0 == dv both ways) — the
            # "DAG" has a 2-cycle and the fixpoint oscillates. The host
            # walk's explicit heap order handles it exactly; force it.
            self.zero_w_unsafe = bool(
                ((w_eff[:e2] < INF_E) & (w_eff[:e2] <= 0)).any()
            )
        else:
            self.adj_w_unsafe = False
            self.zero_w_unsafe = False
        self.e_cap = e_cap
        self.n_cap = n_cap
        self.node_index = index
        self.d_src = jax.device_put(src)
        self.d_dst = jax.device_put(dst)
        self.d_w_eff = jax.device_put(w_eff)
        self.d_adj_w = jax.device_put(adj_w)


def propagate(edges: UcmpEdges, d_dist, leaf_weights: dict[str, int],
              use_prefix_weight: bool):
    """Run the fixpoint; returns (reach, w, overflow) with reach/w as
    HOST numpy arrays ([n_cap] bool, [n_cap] int32). d_dist is the
    device SSSP row from the computing root (ops/ksp2.base_dist).
    overflow=True means the int32 field is untrustworthy — the caller
    must fall back to the host walk."""
    import jax

    if leaf_weights and max(leaf_weights.values()) > (1 << 30):
        return None, None, True
    if not use_prefix_weight and edges.adj_w_unsafe:
        return None, None, True
    # zero-weight edges break DAG membership in BOTH modes (see
    # UcmpEdges); treat exactly like adj_w_unsafe — host walk
    if edges.zero_w_unsafe:
        return None, None, True
    leaf_mask = np.zeros(edges.n_cap, bool)
    leaf_w = np.zeros(edges.n_cap, np.int32)
    for name, weight in leaf_weights.items():
        i = edges.node_index.get(name)
        if i is not None:
            leaf_mask[i] = True
            leaf_w[i] = weight
    fn = _ucmp_fn(edges.e_cap, edges.n_cap, bool(use_prefix_weight))
    reach, w, overflow, rounds = fn(
        edges.d_src, edges.d_dst, edges.d_w_eff, edges.d_adj_w,
        d_dist, jax.device_put(leaf_mask), jax.device_put(leaf_w),
    )
    # same round ledger as every other device fixpoint: executed DAG
    # propagation rounds feed decision.device.rounds alongside the SSSP
    # relaxations
    counters.add_stat_value("decision.device.rounds", int(rounds))
    return np.asarray(reach), np.asarray(w), bool(overflow)
