"""OpenMetrics exposition tests — render/parse round-trip over the
registry, the asyncio scrape server, the Monitor wiring, and the
lint-lane metric-name checker.

The acceptance bar: GET /metrics parses cleanly for 100% of registry
entries — counters as gauges, stat windows as summaries.
"""

import asyncio
import importlib.util
import pathlib

from openr_tpu.messaging import ReplicateQueue
from openr_tpu.runtime.counters import CounterRegistry, counters
from openr_tpu.runtime.metrics_export import (
    MetricsExporter,
    is_valid_metric_name,
    normalize_metric_name,
    parse_exposition,
    render_exposition,
    render_registry,
)
from tests.conftest import run_async


def fresh_registry() -> CounterRegistry:
    reg = CounterRegistry()
    reg.increment("kvstore.node-a.sent_messages", 7)
    reg.set_counter("decision.solver.degraded", 0)
    reg.set_counter("process.memory.rss_mb", 123.5)
    reg.increment("weird name:with spaces/and-slashes")
    for v in (1.0, 2.0, 40.0, 0.25):
        reg.add_stat_value("decision.spf_ms", v)
    reg.add_stat_value("kvstore.flood_rtt_ms", 3.5)
    return reg


class TestNameNormalization:
    def test_dotted_names_become_identifiers(self):
        assert (
            normalize_metric_name("decision.spf_ms")
            == "openr_tpu_decision_spf_ms"
        )
        assert is_valid_metric_name(normalize_metric_name("a.b-c/d e:f"))

    def test_total_on_hostile_input(self):
        # any string maps to a valid identifier (prefix carries the
        # leading-character requirement)
        for hostile in ("0starts.with.digit", "", "∆unicode", "a{b}c"):
            assert is_valid_metric_name(normalize_metric_name(hostile))


class TestRoundTrip:
    def test_every_registry_entry_parses(self):
        reg = fresh_registry()
        counters_snap, stats_snap = reg.export_snapshot()
        text = render_exposition(counters_snap, stats_snap)
        parsed = parse_exposition(text)  # raises on any malformed line

        # 100% of plain counters present with exact values
        for key, val in counters_snap.items():
            assert parsed[(normalize_metric_name(key), ())] == val

        # 100% of stats present: quantiles + sum/count per window, and
        # the _max/_truncated sibling gauges
        for key, windows in stats_snap.items():
            base = normalize_metric_name(key)
            for w, agg in windows.items():
                wl = ("window", w)
                for q, field in (("0.5", "p50"), ("0.95", "p95"),
                                 ("0.99", "p99")):
                    got = parsed[(base, tuple(sorted((wl, ("quantile", q)))))]
                    assert got == agg[field]
                assert parsed[(base + "_sum", (wl,))] == agg["sum"]
                assert parsed[(base + "_count", (wl,))] == agg["count"]
                assert parsed[(base + "_max", (wl,))] == agg["max"]
                assert (base + "_truncated", (wl,)) in parsed
        assert text.rstrip().endswith("# EOF")

    def test_live_registry_renders_valid(self):
        # the process-global registry, whatever other tests left in it,
        # must render text the strict parser fully accepts
        counters.increment("metrics_export_test.probe")
        parsed = parse_exposition(render_registry())
        key = normalize_metric_name("metrics_export_test.probe")
        assert parsed[(key, ())] >= 1.0

    def test_parse_rejects_malformed(self):
        for bad in ("no_value_here", 'name{unclosed="x" 1',
                    "name 1 2 3", "0name 5"):
            try:
                parse_exposition(bad)
            except ValueError:
                continue
            raise AssertionError(f"accepted malformed line: {bad!r}")


async def http_get(port: int, path: str) -> tuple[int, dict, bytes]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(
        f"GET {path} HTTP/1.0\r\nHost: localhost\r\n\r\n".encode()
    )
    await writer.drain()
    raw = await reader.read()
    writer.close()
    await writer.wait_closed()
    head, _, body = raw.partition(b"\r\n\r\n")
    lines = head.decode("latin-1").split("\r\n")
    status = int(lines[0].split()[1])
    headers = {}
    for line in lines[1:]:
        k, _, v = line.partition(":")
        headers[k.strip().lower()] = v.strip()
    return status, headers, body


class TestScrapeServer:
    @run_async
    async def test_get_metrics(self):
        counters.increment("metrics_export_test.scrape_target")
        exporter = MetricsExporter(port=0)
        await exporter.start()
        try:
            assert exporter.port > 0
            status, headers, body = await http_get(exporter.port, "/metrics")
            assert status == 200
            assert headers["content-type"].startswith("text/plain")
            assert int(headers["content-length"]) == len(body)
            parsed = parse_exposition(body.decode())
            key = normalize_metric_name("metrics_export_test.scrape_target")
            assert parsed[(key, ())] >= 1.0
            # the scrape itself is counted
            assert counters.get_counter("monitor.metrics_scrapes") >= 1
        finally:
            await exporter.stop()

    @run_async
    async def test_other_paths_404(self):
        exporter = MetricsExporter(port=0)
        await exporter.start()
        try:
            status, _, _ = await http_get(exporter.port, "/")
            assert status == 404
        finally:
            await exporter.stop()


class TestBuildInfo:
    def test_build_info_gauge_in_registry_render(self):
        import openr_tpu
        from openr_tpu.runtime.metrics_export import build_info_labels

        labels = build_info_labels()
        assert labels["version"] == openr_tpu.__version__
        assert labels["python"]
        assert labels["backend"]
        text = render_registry()
        parsed = parse_exposition(text)
        hits = [
            (name, lbls)
            for (name, lbls) in parsed
            if name == "openr_tpu_build_info"
        ]
        assert len(hits) == 1, hits
        (_, lbls) = hits[0]
        lbl_map = dict(lbls)
        assert lbl_map["version"] == openr_tpu.__version__
        assert "backend" in lbl_map
        assert parsed[hits[0]] == 1.0

    def test_label_values_escaped(self):
        from openr_tpu.runtime.metrics_export import _label_escape

        assert _label_escape('a"b\\c\nd') == 'a\\"b\\\\c\\nd'


class TestConcurrentScrapes:
    @run_async
    async def test_two_concurrent_scrapes_both_parse(self):
        """ISSUE 11 regression: two scrapes racing one exporter must
        BOTH get complete, parseable expositions (the render walks the
        live registry while other fibers mutate it), and each scrape
        records its latency in monitor.metrics_scrape_ms."""
        counters.increment("metrics_export_test.concurrent_probe")
        exporter = MetricsExporter(port=0)
        await exporter.start()
        try:
            async def noisy_writer():
                # registry churn while the scrapes render
                for i in range(200):
                    counters.increment("metrics_export_test.noise")
                    counters.add_stat_value(
                        "metrics_export_test.noise_ms", float(i)
                    )
                    if i % 50 == 0:
                        await asyncio.sleep(0)

            results = await asyncio.gather(
                http_get(exporter.port, "/metrics"),
                http_get(exporter.port, "/metrics"),
                noisy_writer(),
            )
            key = normalize_metric_name(
                "metrics_export_test.concurrent_probe"
            )
            for status, headers, body in results[:2]:
                assert status == 200
                assert int(headers["content-length"]) == len(body)
                parsed = parse_exposition(body.decode())
                assert parsed[(key, ())] >= 1.0
                assert ("openr_tpu_build_info" in
                        {name for (name, _) in parsed})
            # scrape latency is a first-class stat
            stats = counters.get_statistics(
                "monitor.metrics_scrape_ms", windows=(600.0,)
            ).get("monitor.metrics_scrape_ms", {}).get("600", {})
            assert stats.get("count", 0) >= 2, stats
        finally:
            await exporter.stop()


class TestMonitorWiring:
    @run_async
    async def test_monitor_serves_metrics_when_configured(self):
        from openr_tpu.config import MonitorConfig
        from openr_tpu.runtime.monitor import Monitor

        q = ReplicateQueue("test.logSamples")
        mon = Monitor(
            "node-a",
            MonitorConfig(enable_fleet_health=False, metrics_port=0),
            q.get_reader(),
        )
        await mon.start()
        try:
            assert mon.metrics_exporter is not None
            port = mon.metrics_exporter.port
            status, _, body = await http_get(port, "/metrics")
            assert status == 200
            parse_exposition(body.decode())
        finally:
            await mon.stop()
        assert mon.metrics_exporter is None

    @run_async
    async def test_monitor_disabled_by_default(self):
        from openr_tpu.config import MonitorConfig
        from openr_tpu.runtime.monitor import Monitor

        q = ReplicateQueue("test.logSamples2")
        mon = Monitor("node-b", MonitorConfig(enable_fleet_health=False),
                      q.get_reader())
        await mon.start()
        try:
            assert mon.metrics_exporter is None
        finally:
            await mon.stop()


def _load_checker():
    path = (
        pathlib.Path(__file__).resolve().parent.parent
        / "tools" / "check_metric_names.py"
    )
    spec = importlib.util.spec_from_file_location("check_metric_names", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestMetricNameChecker:
    def test_codebase_is_clean(self):
        chk = _load_checker()
        pkg = (
            pathlib.Path(__file__).resolve().parent.parent / "openr_tpu"
        )
        counter_names, stat_names, errors = chk.collect(pkg)
        errors += chk.check(counter_names, stat_names)
        assert not errors, errors
        # sanity: the walk actually found the fabric's families
        assert "decision.route_builds" in counter_names
        assert "decision.spf_ms" in stat_names
        # f-string placeholders abstracted, not dropped
        assert any("X" in name for name in counter_names)

    def test_checker_catches_collision(self):
        chk = _load_checker()
        errors = chk.check(
            {"a.b": "x.py:1", "a_b": "y.py:2"}, {}
        )
        assert errors and "collide" in errors[0]

    def test_checker_catches_stat_suffix_collision(self):
        chk = _load_checker()
        errors = chk.check({"a.b_max": "x.py:1"}, {"a.b": "y.py:2"})
        assert errors
