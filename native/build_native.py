"""Build the native extension in place at the REPO ROOT (so plain
`import openr_tpu_native` works for the daemon and tests):

    python native/build_native.py

(role of the reference's cmake openrlib target for openr/nl). The
platform layer auto-detects the built module and uses it for large
syncs; everything works without it (pure-Python fallback)."""

import os
import sys

from setuptools import Extension, setup

root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
os.chdir(root)
sys.argv[1:] = []

setup(
    name="openr-tpu-native",
    ext_modules=[
        Extension(
            "openr_tpu_native",
            sources=["native/netlink_bulk.cpp"],
            extra_compile_args=["-O2", "-std=c++17"],
        )
    ],
    script_args=["build_ext", "--inplace"],
)
