"""Platform layer tests: FibService over RPC (in-process and two-process)
and the rtnetlink client.

Role of the reference's NetlinkFibHandlerTest/Benchmark +
openr/nl/tests — kernel-mutating cases gate on CAP_NET_ADMIN (README
"some tests require sudo"); message (de)serialization and the RPC seam
run everywhere.
"""

import asyncio
import json
import os
import re
import signal
import socket
import subprocess
import sys
import time

import pytest

from openr_tpu.config import FibConfig
from openr_tpu.decision.rib import (
    DecisionRouteUpdate,
    NextHop,
    RibUnicastEntry,
    RouteUpdateType,
)
from openr_tpu.fib.fib import Fib
from openr_tpu.fib.fib_service import FibUpdateError
from openr_tpu.messaging import ReplicateQueue
from openr_tpu.platform.fib_handler import (
    FibPlatformServer,
    MemoryDataplane,
    RemoteFibService,
    wait_for_fib_service,
)
from tests.conftest import run_async

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def route(prefix, nbr="peer", metric=10):
    return RibUnicastEntry(
        prefix=prefix,
        nexthops=frozenset(
            {NextHop(address="10.0.0.2", if_name="if0",
                     neighbor_node_name=nbr, metric=metric)}
        ),
        igp_cost=metric,
    )


class TestRemoteFibService:
    @run_async
    async def test_program_and_dump_roundtrip(self):
        server = FibPlatformServer()
        await server.start()
        svc = RemoteFibService(port=server.port)
        try:
            assert await wait_for_fib_service(svc, timeout_s=5) > 0
            await svc.add_unicast_routes(
                0, [route("10.1.0.0/24"), route("10.2.0.0/24")]
            )
            await svc.delete_unicast_routes(0, ["10.2.0.0/24"])
            table = await svc.get_route_table()
            assert set(table["unicast"]) == {"10.1.0.0/24"}
            entry = table["unicast"]["10.1.0.0/24"]
            assert entry["igp_cost"] == 10
            assert entry["nexthops"][0]["neighbor_node_name"] == "peer"

            await svc.sync_fib(0, [route("10.3.0.0/24")])
            table = await svc.get_route_table()
            assert set(table["unicast"]) == {"10.3.0.0/24"}
        finally:
            await svc.close()
            await server.stop()

    @run_async
    async def test_partial_failure_crosses_process_boundary(self):
        dp = MemoryDataplane()
        dp.fail_prefixes.add("10.9.0.0/24")
        server = FibPlatformServer(dp)
        await server.start()
        svc = RemoteFibService(port=server.port)
        try:
            with pytest.raises(FibUpdateError) as exc:
                await svc.add_unicast_routes(
                    0, [route("10.8.0.0/24"), route("10.9.0.0/24")]
                )
            assert exc.value.failed_prefixes == ["10.9.0.0/24"]
            table = await svc.get_route_table()
            assert set(table["unicast"]) == {"10.8.0.0/24"}
        finally:
            await svc.close()
            await server.stop()

    @run_async
    async def test_fib_actor_programs_remote_service(self):
        """The full Fib actor against the out-of-process seam: initial
        FULL_SYNC then incremental update, with a partial failure
        exercising dirty-route retry across the RPC boundary."""
        dp = MemoryDataplane()
        server = FibPlatformServer(dp)
        await server.start()
        svc = RemoteFibService(port=server.port)
        routes_q = ReplicateQueue("routes")
        fib_updates = ReplicateQueue("fibUpdates")
        fib = Fib(
            "node-a",
            FibConfig(route_delete_delay_ms=0),
            svc,
            routes_q.get_reader(),
            fib_updates,
        )
        await fib.start()
        try:
            upd = DecisionRouteUpdate(type=RouteUpdateType.FULL_SYNC)
            upd.unicast_routes_to_update["10.1.0.0/24"] = route("10.1.0.0/24")
            routes_q.push(upd)

            async def programmed():
                while "10.1.0.0/24" not in dp.unicast:
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(programmed(), 10)

            inc = DecisionRouteUpdate(type=RouteUpdateType.INCREMENTAL)
            inc.unicast_routes_to_update["10.2.0.0/24"] = route("10.2.0.0/24")
            inc.unicast_routes_to_delete.append("10.1.0.0/24")
            routes_q.push(inc)

            async def updated():
                while (
                    "10.2.0.0/24" not in dp.unicast
                    or "10.1.0.0/24" in dp.unicast
                ):
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(updated(), 10)
        finally:
            routes_q.close()
            await fib.stop()
            await svc.close()
            await server.stop()


class TestNetlinkMessages:
    def test_route_message_roundtrip_v4_single_nexthop(self):
        from openr_tpu.platform import netlink as nl

        r = nl.NlRoute(
            prefix="10.5.0.0/24",
            nexthops=(nl.NlNextHop(gateway="10.0.0.1", ifindex=3),),
            metric=20,
            table=254,
        )
        parsed = nl._parse_route_msg(nl._build_route_msg(r))
        assert parsed.prefix == "10.5.0.0/24"
        assert parsed.metric == 20
        assert parsed.table == 254
        assert parsed.protocol == nl.PROTO_OPENR
        (nh,) = parsed.nexthops
        assert nh.gateway == "10.0.0.1" and nh.ifindex == 3

    def test_route_message_roundtrip_v6_ecmp(self):
        from openr_tpu.platform import netlink as nl

        r = nl.NlRoute(
            prefix="fd00:1::/64",
            nexthops=(
                nl.NlNextHop(gateway="fe80::1", ifindex=2, weight=2),
                nl.NlNextHop(gateway="fe80::2", ifindex=4, weight=1),
            ),
        )
        parsed = nl._parse_route_msg(nl._build_route_msg(r))
        assert parsed.prefix == "fd00:1::/64"
        gws = {(nh.gateway, nh.ifindex, nh.weight) for nh in parsed.nexthops}
        assert gws == {("fe80::1", 2, 2), ("fe80::2", 4, 1)}

    def test_extended_table_id_attribute(self):
        from openr_tpu.platform import netlink as nl

        r = nl.NlRoute(prefix="10.0.0.0/8", table=10099)
        parsed = nl._parse_route_msg(nl._build_route_msg(r))
        assert parsed.table == 10099

    def test_rule_message_roundtrip(self):
        """fib_rule_hdr + FRA attrs both directions (ref
        NetlinkRuleMessage::addRule/parseMessage)."""
        from openr_tpu.platform import netlink as nl

        r = nl.NlRule(
            family=socket.AF_INET, action=nl.FR_ACT_TO_TBL, table=100,
            priority=1000, fwmark=0x2a,
        )
        parsed = nl._parse_rule_msg(nl._build_rule_msg(r))
        assert parsed == r

    def test_rule_extended_table_id(self):
        """Tables >255 overflow the u8 header field into FRA_TABLE."""
        from openr_tpu.platform import netlink as nl

        r = nl.NlRule(family=socket.AF_INET6, table=70000, priority=7)
        parsed = nl._parse_rule_msg(nl._build_rule_msg(r))
        assert parsed.table == 70000 and parsed.family == socket.AF_INET6

    def test_neighbor_message_parse(self):
        """ndmsg + NDA_DST/NDA_LLADDR -> NlNeighbor (ref
        NetlinkNeighborMessage parsing)."""
        from openr_tpu.platform import netlink as nl

        body = nl._NDMSG.pack(
            socket.AF_INET, 0, 0, 4, nl.NUD_REACHABLE, 0, 0
        )
        body += nl._rta(nl.NDA_DST, socket.inet_aton("10.0.0.9"))
        body += nl._rta(nl.NDA_LLADDR, bytes.fromhex("0202aabbccdd"))
        n = nl._parse_neigh_msg(body)
        assert n.ifindex == 4
        assert n.destination == "10.0.0.9"
        assert n.lladdr == "02:02:aa:bb:cc:dd"
        assert n.is_reachable

    def test_neighbor_unresolved_and_failed_states(self):
        from openr_tpu.platform import netlink as nl

        body = nl._NDMSG.pack(
            socket.AF_INET6, 0, 0, 2, nl.NUD_FAILED, 0, 0
        )
        body += nl._rta(
            nl.NDA_DST, socket.inet_pton(socket.AF_INET6, "fe80::9")
        )
        n = nl._parse_neigh_msg(body)
        assert n.destination == "fe80::9"
        assert n.lladdr == "" and not n.is_reachable


def _can_net_admin() -> bool:
    try:
        s = socket.socket(
            socket.AF_NETLINK, socket.SOCK_RAW, socket.NETLINK_ROUTE
        )
        s.close()
    except OSError:
        return False
    return os.geteuid() == 0


class TestNetlinkKernel:
    @run_async
    async def test_dump_main_table(self):
        """Unprivileged read path: RTM_GETROUTE dump parses."""
        from openr_tpu.platform import netlink as nl

        sock = nl.NetlinkRouteSocket()
        try:
            sock.open()
        except OSError:
            pytest.skip("no AF_NETLINK")
        try:
            routes = await sock.get_routes(socket.AF_INET)
            assert isinstance(routes, list)
        finally:
            sock.close()

    @pytest.mark.skipif(not _can_net_admin(), reason="needs CAP_NET_ADMIN")
    @run_async
    async def test_add_delete_route_in_kernel(self):
        """Real kernel route programming in a private table, verified by
        dump, then removed (ref NetlinkProtocolSocketTest)."""
        from openr_tpu.platform import netlink as nl

        lo = socket.if_nametoindex("lo")
        sock = nl.NetlinkRouteSocket()
        sock.open()
        r = nl.NlRoute(
            prefix="10.254.253.0/24",
            nexthops=(nl.NlNextHop(ifindex=lo),),
            metric=42,
            table=10099,
        )
        try:
            await sock.add_route(r)
            got = await sock.get_routes(
                socket.AF_INET, table=10099, protocol=nl.PROTO_OPENR
            )
            assert any(x.prefix == "10.254.253.0/24" for x in got), got
            await sock.delete_route(r)
            got = await sock.get_routes(
                socket.AF_INET, table=10099, protocol=nl.PROTO_OPENR
            )
            assert not any(x.prefix == "10.254.253.0/24" for x in got)
        finally:
            sock.close()

    @pytest.mark.skipif(not _can_net_admin(), reason="needs CAP_NET_ADMIN")
    @run_async
    async def test_netlink_dataplane_sync_semantics(self):
        """NetlinkDataplane.sync removes stale daemon-owned routes and
        leaves foreign routes alone."""
        from openr_tpu.platform.fib_handler import NetlinkDataplane

        dp = NetlinkDataplane(table=10098)
        nh = [{"address": "", "if_name": "lo", "weight": 0}]
        try:
            failed = await dp.sync_unicast(
                {"10.254.1.0/24": {"nexthops": nh, "igp_cost": 7},
                 "10.254.2.0/24": {"nexthops": nh, "igp_cost": 7}}
            )
            assert not failed
            failed = await dp.sync_unicast(
                {"10.254.2.0/24": {"nexthops": nh, "igp_cost": 7}}
            )
            assert not failed
            got = await dp.nl.get_routes(socket.AF_INET, table=10098)
            prefixes = {r.prefix for r in got}
            assert "10.254.2.0/24" in prefixes
            assert "10.254.1.0/24" not in prefixes
        finally:
            await dp.delete_unicast(["10.254.2.0/24"])
            dp.nl.close()

    @pytest.mark.skipif(not _can_net_admin(), reason="needs CAP_NET_ADMIN")
    @run_async
    async def test_metric_change_replaces_kernel_route(self):
        """Regression (lab 201): the kernel keys routes on
        (prefix, metric), so a metric change (RTT drift, redistribution
        distance) must not leave both entries installed."""
        from openr_tpu.platform.fib_handler import NetlinkDataplane

        dp = NetlinkDataplane(table=10097)
        nh = [{"address": "", "if_name": "lo", "weight": 0}]
        p = "10.254.3.0/24"
        try:
            assert not await dp.add_unicast(
                {p: {"nexthops": nh, "igp_cost": 17}}
            )
            assert not await dp.add_unicast(
                {p: {"nexthops": nh, "igp_cost": 24}}
            )
            got = [
                r
                for r in await dp.nl.get_routes(
                    socket.AF_INET, table=10097
                )
                if r.prefix == p
            ]
            assert len(got) == 1 and got[0].metric == 24, got

            # restart (lost metric record) + sync at a third metric:
            # the duplicate-clearing pass removes the orphan
            dp2 = NetlinkDataplane(table=10097)
            try:
                assert not await dp2.sync_unicast(
                    {p: {"nexthops": nh, "igp_cost": 31}}
                )
                got = [
                    r
                    for r in await dp2.nl.get_routes(
                        socket.AF_INET, table=10097
                    )
                    if r.prefix == p
                ]
                assert len(got) == 1 and got[0].metric == 31, got
                # delete removes the (prefix, metric) we programmed
                assert not await dp2.delete_unicast([p])
                got = await dp2.nl.get_routes(socket.AF_INET, table=10097)
                assert not [r for r in got if r.prefix == p]
            finally:
                dp2.nl.close()
        finally:
            await dp.delete_unicast([p])
            dp.nl.close()


class _ScriptedNetlink:
    """Records the exact order of kernel mutations; optionally fails
    specific (op, prefix, metric) calls with an errno."""

    def __init__(self, fail=()):
        self.ops: list[tuple[str, str, int]] = []
        self.fail = dict(fail)  # (op, prefix, metric) -> errno

    async def _do(self, op, r):
        self.ops.append((op, r.prefix, r.metric))
        eno = self.fail.get((op, r.prefix, r.metric))
        if eno is not None:
            raise OSError(eno, os.strerror(eno))

    async def add_route(self, r):
        await self._do("add", r)

    async def delete_route(self, r):
        await self._do("del", r)

    def close(self):
        pass


def _scripted_dataplane(fake):
    from openr_tpu.platform.fib_handler import NetlinkDataplane

    dp = NetlinkDataplane.__new__(NetlinkDataplane)
    dp.table = 254
    dp.nl = fake
    dp._opened = True
    dp.mpls = {}
    dp._metric = {}
    dp._stale = {}
    dp.mpls_kernel = False
    return dp


class TestMakeBeforeBreak:
    """Regression: a metric change must program the NEW-metric kernel
    route before deleting the old-metric one — delete-first opens a
    forwarding gap, and blackholes the prefix if the add then fails."""

    NH = [{"address": "", "if_name": "", "weight": 0}]

    @run_async
    async def test_add_precedes_old_metric_delete(self):
        fake = _ScriptedNetlink()
        dp = _scripted_dataplane(fake)
        p = "10.9.0.0/24"
        assert not await dp.add_unicast({p: {"nexthops": self.NH,
                                             "igp_cost": 10}})
        assert not await dp.add_unicast({p: {"nexthops": self.NH,
                                             "igp_cost": 20}})
        assert fake.ops == [
            ("add", p, 10), ("add", p, 20), ("del", p, 10)
        ]
        assert dp._metric[p] == 20 and not dp._stale

    @run_async
    async def test_failed_add_keeps_old_route_installed(self):
        import errno

        p = "10.9.1.0/24"
        fake = _ScriptedNetlink(fail={("add", p, 20): errno.ENOBUFS})
        dp = _scripted_dataplane(fake)
        assert not await dp.add_unicast({p: {"nexthops": self.NH,
                                             "igp_cost": 10}})
        failed = await dp.add_unicast({p: {"nexthops": self.NH,
                                           "igp_cost": 20}})
        assert failed == [p]
        # the old-metric route was never deleted: forwarding holds
        assert ("del", p, 10) not in fake.ops
        assert dp._metric[p] == 10

    @run_async
    async def test_failed_cleanup_parks_in_stale_ledger_and_retries(self):
        import errno

        p = "10.9.2.0/24"
        fake = _ScriptedNetlink(fail={("del", p, 10): errno.EBUSY})
        dp = _scripted_dataplane(fake)
        assert not await dp.add_unicast({p: {"nexthops": self.NH,
                                             "igp_cost": 10}})
        failed = await dp.add_unicast({p: {"nexthops": self.NH,
                                           "igp_cost": 20}})
        # new route IS live; the prefix is reported failed only so the
        # Fib actor retries the duplicate cleanup
        assert failed == [p]
        assert dp._metric[p] == 20 and dp._stale == {p: {10}}
        fake.fail.clear()
        assert not await dp.add_unicast({p: {"nexthops": self.NH,
                                             "igp_cost": 20}})
        assert fake.ops[-1] == ("del", p, 10)
        assert not dp._stale

    @run_async
    async def test_withdraw_clears_stale_duplicates(self):
        import errno

        p = "10.9.3.0/24"
        fake = _ScriptedNetlink(fail={("del", p, 10): errno.EBUSY})
        dp = _scripted_dataplane(fake)
        await dp.add_unicast({p: {"nexthops": self.NH, "igp_cost": 10}})
        await dp.add_unicast({p: {"nexthops": self.NH, "igp_cost": 20}})
        fake.fail.clear()
        assert not await dp.delete_unicast([p])
        assert {("del", p, 20), ("del", p, 10)} <= set(fake.ops)
        assert not dp._metric and not dp._stale


FAST_TIMERS = {
    "hello_time_s": 0.1,
    "fastinit_hello_time_ms": 30,
    "keepalive_time_s": 0.1,
    "hold_time_s": 1.0,
    "graceful_restart_time_s": 2.0,
    "handshake_time_ms": 50,
    "min_packets_per_sec": 0,
}


def test_daemon_with_out_of_process_platform(tmp_path):
    """Three processes: platform agent + two daemons, daemon A programs
    its routes into the agent over RPC (ref Main.cpp waitForFibService +
    platform_linux deployment shape)."""
    agent = subprocess.Popen(
        [sys.executable, "-m", "openr_tpu.platform.main", "--port", "0"],
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    procs = [agent]
    try:
        line = ""
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            line = agent.stdout.readline()
            if line.startswith("READY"):
                break
        m = re.match(r"READY fib=(\d+)", line)
        assert m, f"agent not ready: {line!r}"
        fib_port = int(m.group(1))

        port_a, port_b = 16671, 16672
        cfgs = {}
        for name, udp in (("plat-a", port_a), ("plat-b", port_b)):
            cfg = {
                "node_name": name,
                "openr_ctrl_port": 0,
                "spark_config": {
                    **FAST_TIMERS,
                    "neighbor_discovery_port": udp,
                },
                "decision_config": {
                    "debounce_min_ms": 10, "debounce_max_ms": 50,
                },
                "kvstore_config": {},
                "enable_watchdog": False,
                "originated_prefixes": [
                    {"prefix": f"10.77.{1 if name == 'plat-a' else 2}.0/24",
                     "install_to_fib": False}
                ],
            }
            path = tmp_path / f"{name}.conf"
            path.write_text(json.dumps(cfg))
            cfgs[name] = str(path)

        def spawn(name, iface_port, peer_port, extra=()):
            return subprocess.Popen(
                [
                    sys.executable, "-m", "openr_tpu.main",
                    "--config", cfgs[name],
                    "--interface", f"if0=127.0.0.1:{iface_port}",
                    "--peer", f"if0=127.0.0.1:{peer_port}",
                    "--ctrl-port", "0",
                    *extra,
                ],
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )

        pa = spawn("plat-a", port_a, port_b,
                   ("--fib-service", f"127.0.0.1:{fib_port}"))
        pb = spawn("plat-b", port_b, port_a)
        procs += [pa, pb]

        for p in (pa, pb):
            deadline = time.monotonic() + 30
            ok = False
            while time.monotonic() < deadline:
                line = p.stdout.readline()
                if line.startswith("READY"):
                    ok = True
                    break
            assert ok, "daemon did not report READY"

        # poll the AGENT's table for b's prefix programmed by daemon a
        async def check():
            svc = RemoteFibService(port=fib_port)
            try:
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    table = await svc.get_route_table()
                    if "10.77.2.0/24" in table["unicast"]:
                        return table
                    await asyncio.sleep(0.3)
                raise AssertionError(f"route never programmed: {table}")
            finally:
                await svc.close()

        table = asyncio.run(check())
        nhs = table["unicast"]["10.77.2.0/24"]["nexthops"]
        assert nhs and nhs[0]["neighbor_node_name"] == "plat-b"

        for p in (pa, pb):
            p.send_signal(signal.SIGTERM)
            assert p.wait(timeout=15) == 0
        agent.send_signal(signal.SIGTERM)
        assert agent.wait(timeout=15) == 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


class TestNativeBulk:
    def test_pack_format_lengths(self):
        from openr_tpu.platform import netlink as nl

        buf = nl.pack_bulk_routes(
            [
                nl.NlRoute(
                    prefix="10.0.0.0/24",
                    nexthops=(
                        nl.NlNextHop(gateway="10.0.0.1", ifindex=2),
                        nl.NlNextHop(ifindex=3, weight=2),
                    ),
                    metric=5,
                )
            ]
        )
        # header (8 + 16) + 2 nexthops x (8 + 16)
        assert len(buf) == 24 + 2 * 24

    @pytest.mark.skipif(not _can_net_admin(), reason="needs CAP_NET_ADMIN")
    @run_async
    async def test_bulk_programs_and_deletes_in_kernel(self):
        from openr_tpu.platform import netlink as nl

        if not nl.native_bulk_available():
            pytest.skip("native module not built (python native/build_native.py)")
        lo = socket.if_nametoindex("lo")
        routes = [
            nl.NlRoute(
                prefix=f"10.253.{i >> 8}.{i & 0xFF}/32",
                nexthops=(nl.NlNextHop(ifindex=lo),),
                metric=3,
                table=10095,
            )
            for i in range(2000)
        ]
        ok, err = nl.bulk_route_op(0, 10095, nl.PROTO_OPENR, routes)
        assert (ok, err) == (2000, 0)
        sock = nl.NetlinkRouteSocket()
        sock.open()
        try:
            got = await sock.get_routes(
                socket.AF_INET, table=10095, protocol=nl.PROTO_OPENR
            )
            assert len(got) == 2000
        finally:
            sock.close()
        ok, err = nl.bulk_route_op(1, 10095, nl.PROTO_OPENR, routes)
        assert (ok, err) == (2000, 0)

    @pytest.mark.skipif(not _can_net_admin(), reason="needs CAP_NET_ADMIN")
    @run_async
    async def test_dataplane_uses_bulk_for_large_sync(self):
        from openr_tpu.platform import netlink as nl
        from openr_tpu.platform.fib_handler import NetlinkDataplane

        if not nl.native_bulk_available():
            pytest.skip("native module not built")
        dp = NetlinkDataplane(table=10094)
        nh = [{"address": "", "if_name": "lo", "weight": 0}]
        routes = {
            f"10.252.{i >> 8}.{i & 0xFF}/32": {"nexthops": nh, "igp_cost": 2}
            for i in range(500)
        }
        try:
            failed = await dp.sync_unicast(routes)
            assert not failed
            got = await dp.nl.get_routes(
                socket.AF_INET, table=10094, protocol=nl.PROTO_OPENR
            )
            assert len(got) == 500
        finally:
            await dp.delete_unicast(sorted(routes))
            dp.nl.close()


class TestNetlinkLinkAddr:
    """Link/addr dumps + event subscription (ref NetlinkProtocolSocket
    link/addr messages + event queue, NetlinkProtocolSocket.h:29-31)."""

    @run_async
    async def test_link_and_addr_dump(self):
        """Unprivileged: every host has lo with 127.0.0.1/8."""
        from openr_tpu.platform.netlink import NetlinkRouteSocket

        nl = NetlinkRouteSocket()
        nl.open()
        try:
            links = await nl.get_links()
            by_name = {l.name: l for l in links}
            assert "lo" in by_name
            assert by_name["lo"].is_loopback
            addrs = await nl.get_addrs(socket.AF_INET)
            lo_addrs = [
                a.prefix for a in addrs
                if a.ifindex == by_name["lo"].ifindex
            ]
            assert "127.0.0.1/8" in lo_addrs
        finally:
            nl.close()

    @pytest.mark.skipif(not _can_net_admin(), reason="needs CAP_NET_ADMIN")
    @run_async
    async def test_veth_lifecycle_events(self):
        """Create a veth pair, add an address, flip it down, delete it —
        each kernel action must surface as a subscription event."""
        from openr_tpu.platform.netlink import (
            RTMGRP_IPV4_IFADDR,
            RTMGRP_IPV6_IFADDR,
            RTMGRP_LINK,
            NetlinkRouteSocket,
        )

        name = f"ovt{os.getpid() % 10000}"
        events: asyncio.Queue = asyncio.Queue()
        nl = NetlinkRouteSocket(
            event_cb=lambda kind, obj: events.put_nowait((kind, obj))
        )
        nl.open(groups=RTMGRP_LINK | RTMGRP_IPV4_IFADDR | RTMGRP_IPV6_IFADDR)

        def sh(*args):
            subprocess.run(args, check=True, capture_output=True)

        async def wait_for(pred, timeout=5.0):
            deadline = time.monotonic() + timeout
            while True:
                remaining = deadline - time.monotonic()
                assert remaining > 0, "event not observed"
                kind, obj = await asyncio.wait_for(events.get(), remaining)
                if pred(kind, obj):
                    return kind, obj

        try:
            sh("ip", "link", "add", name, "type", "veth",
               "peer", "name", f"{name}p")
            await wait_for(
                lambda k, o: k == "link" and o.name == name
            )
            sh("ip", "addr", "add", "10.254.77.1/30", "dev", name)
            _, addr = await wait_for(
                lambda k, o: k == "addr" and o.prefix == "10.254.77.1/30"
            )
            sh("ip", "link", "set", name, "up")
            sh("ip", "link", "set", f"{name}p", "up")
            await wait_for(
                lambda k, o: k == "link" and o.name == name and o.is_up
            )
            sh("ip", "link", "set", name, "down")
            await wait_for(
                lambda k, o: k == "link" and o.name == name and not o.is_up
            )
            sh("ip", "link", "del", name)
            await wait_for(
                lambda k, o: k == "link_del" and o.name == name
            )
        finally:
            subprocess.run(
                ["ip", "link", "del", name], capture_output=True
            )
            nl.close()

    @run_async
    async def test_neighbor_dump(self):
        """Unprivileged: RTM_GETNEIGH dump parses into NlNeighbor
        entries (ref getAllNeighbors)."""
        from openr_tpu.platform.netlink import NetlinkRouteSocket, NlNeighbor

        nl = NetlinkRouteSocket()
        try:
            nl.open()
        except OSError:
            pytest.skip("no AF_NETLINK")
        try:
            neighbors = await nl.get_neighbors()
            assert all(isinstance(n, NlNeighbor) for n in neighbors)
            for n in neighbors:
                assert n.destination  # parsed an address for every entry
        finally:
            nl.close()

    @pytest.mark.skipif(not _can_net_admin(), reason="needs CAP_NET_ADMIN")
    @run_async
    async def test_rule_lifecycle_with_events(self):
        """Add a policy rule, see it in the dump AND as a subscription
        event, delete it, see the deletion (ref addRule/deleteRule/
        getAllRules + Rule events)."""
        from openr_tpu.platform.netlink import (
            FR_ACT_TO_TBL,
            RTMGRP_IPV4_RULE,
            NetlinkRouteSocket,
            NlRule,
        )

        # separate listener: the kernel's group broadcast excludes the
        # portid that issued the change, so a socket never sees events
        # for its own mutations
        events: asyncio.Queue = asyncio.Queue()
        watcher = NetlinkRouteSocket(
            event_cb=lambda kind, obj: events.put_nowait((kind, obj))
        )
        watcher.open(groups=RTMGRP_IPV4_RULE)
        nl = NetlinkRouteSocket()
        nl.open()
        rule = NlRule(
            family=socket.AF_INET, action=FR_ACT_TO_TBL, table=10077,
            priority=30077,
        )

        async def wait_for(pred, timeout=5.0):
            deadline = time.monotonic() + timeout
            while True:
                remaining = deadline - time.monotonic()
                assert remaining > 0, "rule event not observed"
                kind, obj = await asyncio.wait_for(events.get(), remaining)
                if pred(kind, obj):
                    return obj

        try:
            await nl.add_rule(rule)
            await wait_for(
                lambda k, o: k == "rule" and o.priority == 30077
            )
            rules = await nl.get_rules(socket.AF_INET)
            mine = [r for r in rules if r.priority == 30077]
            assert mine and mine[0].table == 10077
            await nl.delete_rule(rule)
            await wait_for(
                lambda k, o: k == "rule_del" and o.priority == 30077
            )
            rules = await nl.get_rules(socket.AF_INET)
            assert not [r for r in rules if r.priority == 30077]
        finally:
            try:
                await nl.delete_rule(rule)
            except OSError:
                pass
            nl.close()
            watcher.close()

    @pytest.mark.skipif(not _can_net_admin(), reason="needs CAP_NET_ADMIN")
    @run_async
    async def test_interface_monitor_feeds_link_monitor(self):
        """NetlinkInterfaceMonitor end-to-end: discovery + live up/down
        propagate as InterfaceInfo callbacks (what LinkMonitor consumes);
        downing the iface reports is_up=False immediately."""
        from openr_tpu.platform.iface_monitor import NetlinkInterfaceMonitor

        name = f"ovm{os.getpid() % 10000}"

        def sh(*args):
            subprocess.run(args, check=True, capture_output=True)

        infos: asyncio.Queue = asyncio.Queue()
        mon = NetlinkInterfaceMonitor(
            on_interface=infos.put_nowait,
            include_regexes=[re.escape(name)],
        )

        async def next_info(pred, timeout=5.0):
            deadline = time.monotonic() + timeout
            while True:
                remaining = deadline - time.monotonic()
                assert remaining > 0, "InterfaceInfo not observed"
                info = await asyncio.wait_for(infos.get(), remaining)
                if pred(info):
                    return info

        try:
            sh("ip", "link", "add", name, "type", "veth",
               "peer", "name", f"{name}p")
            sh("ip", "addr", "add", "10.254.78.1/30", "dev", name)
            await mon.start()
            # discovered at start, down, with its (global) address
            info = await next_info(lambda i: i.if_name == name)
            assert not info.is_up
            assert "10.254.78.1/30" in info.networks
            sh("ip", "link", "set", name, "up")
            sh("ip", "link", "set", f"{name}p", "up")
            await next_info(lambda i: i.if_name == name and i.is_up)
            sh("ip", "link", "set", name, "down")
            await next_info(lambda i: i.if_name == name and not i.is_up)
            # loopback and unmatched interfaces never surface
            assert mon.interfaces().keys() == {name}
        finally:
            subprocess.run(
                ["ip", "link", "del", name], capture_output=True
            )
            mon.close()


class TestMplsEncode:
    """AF_MPLS wire format (ref NetlinkRouteMessage.cpp:618-769) —
    byte-level assertions, no kernel needed."""

    def test_label_stack_bos_bit(self):
        from openr_tpu.platform.netlink import _mpls_label_stack

        one = _mpls_label_stack((100,))
        assert one == (100 << 12 | 1 << 8).to_bytes(4, "big")
        stack = _mpls_label_stack((100, 200))
        assert len(stack) == 8
        first = int.from_bytes(stack[:4], "big")
        last = int.from_bytes(stack[4:], "big")
        assert first >> 12 == 100 and not first & (1 << 8)
        assert last >> 12 == 200 and last & (1 << 8)

    def test_mpls_route_roundtrip_via_parser(self):
        """encode -> parse yields the same route (swap + php + pop)."""
        from openr_tpu.platform.netlink import (
            NlMplsRoute,
            NlNextHop,
            _build_mpls_route_msg,
            _parse_mpls_route_msg,
        )

        for route in (
            # swap: one nexthop with a new label
            NlMplsRoute(
                label=100,
                nexthops=(
                    NlNextHop(gateway="10.0.0.2", ifindex=3,
                              out_labels=(200,)),
                ),
            ),
            # php: pop and forward (no out labels)
            NlMplsRoute(
                label=101,
                nexthops=(NlNextHop(gateway="fe80::1", ifindex=2),),
            ),
            # ECMP swap group
            NlMplsRoute(
                label=102,
                nexthops=(
                    NlNextHop(gateway="10.0.0.2", ifindex=3,
                              out_labels=(201,), weight=1),
                    NlNextHop(gateway="10.0.0.6", ifindex=4,
                              out_labels=(202,), weight=1),
                ),
            ),
        ):
            body = _build_mpls_route_msg(route)
            parsed = _parse_mpls_route_msg(body)
            assert parsed is not None
            assert parsed.label == route.label
            assert {
                (nh.gateway, nh.ifindex, nh.out_labels)
                for nh in parsed.nexthops
            } == {
                (nh.gateway, nh.ifindex, nh.out_labels)
                for nh in route.nexthops
            }

    def test_unicast_push_encap_encoded(self):
        """An IP route whose nexthop pushes labels must carry LWTUNNEL
        MPLS encap attributes."""
        from openr_tpu.platform.netlink import (
            RTA_ENCAP,
            RTA_ENCAP_TYPE,
            NlNextHop,
            NlRoute,
            _build_route_msg,
            _rta,
        )
        import struct as _struct

        route = NlRoute(
            prefix="10.1.0.0/24",
            nexthops=(
                NlNextHop(gateway="10.0.0.2", ifindex=3,
                          out_labels=(300, 301)),
            ),
        )
        body = _build_route_msg(route)
        assert _rta(RTA_ENCAP_TYPE, _struct.pack("=H", 1)) in body
        # the encap attr nests MPLS_IPTUNNEL_DST with the stack
        assert (300 << 12).to_bytes(4, "big") in body
        assert (301 << 12 | 1 << 8).to_bytes(4, "big") in body

    def test_bulk_rejects_encap(self):
        """The native bulk format cannot carry encap — packing must
        refuse rather than silently strip labels."""
        from openr_tpu.platform.netlink import (
            NlNextHop,
            NlRoute,
            pack_bulk_routes,
        )

        with pytest.raises(ValueError, match="MPLS"):
            pack_bulk_routes(
                [
                    NlRoute(
                        prefix="10.1.0.0/24",
                        nexthops=(
                            NlNextHop(gateway="10.0.0.2",
                                      out_labels=(300,)),
                        ),
                    )
                ]
            )

    @pytest.mark.skipif(
        not (_can_net_admin() and os.path.isdir("/proc/sys/net/mpls")),
        reason="needs CAP_NET_ADMIN + mpls_router",
    )
    @run_async
    async def test_kernel_mpls_route_programs(self):
        """Where the kernel MPLS dataplane exists: program a label route
        and read it back (the netns-lab path)."""
        from openr_tpu.platform.netlink import (
            PROTO_OPENR,
            NetlinkRouteSocket,
            NlMplsRoute,
            NlNextHop,
        )

        subprocess.run(
            ["sysctl", "-w", "net.mpls.platform_labels=1000"],
            check=True, capture_output=True,
        )
        nl = NetlinkRouteSocket()
        nl.open()
        try:
            lo = socket.if_nametoindex("lo")
            route = NlMplsRoute(
                label=500, nexthops=(NlNextHop(ifindex=lo),)
            )
            await nl.add_mpls_route(route)
            try:
                routes = await nl.get_mpls_routes(PROTO_OPENR)
                assert any(r.label == 500 for r in routes)
            finally:
                await nl.delete_mpls_route(route)
        finally:
            nl.close()
