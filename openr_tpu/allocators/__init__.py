from openr_tpu.allocators.prepend_label import (  # noqa: F401
    LabelRangeExhausted,
    PrependLabelAllocator,
)
from openr_tpu.allocators.range_allocator import (  # noqa: F401
    ALLOC_PREFIX_MARKER,
    STATIC_ALLOC_KEY,
    PrefixAllocator,
    RangeAllocator,
    StaticPrefixAllocator,
)
