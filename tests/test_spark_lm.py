"""Spark + LinkMonitor tests over the MockIoMesh seam
(ref openr/spark/tests/SparkTest.cpp with MockIoProvider, and
openr/link-monitor/tests/LinkMonitorTest.cpp)."""

import asyncio

from openr_tpu.config import LinkMonitorConfig, SparkConfig
from openr_tpu.kvstore.wrapper import wait_until
from openr_tpu.link_monitor import LinkMonitor, get_rtt_metric
from openr_tpu.messaging import ReplicateQueue
from openr_tpu.serde import deserialize
from openr_tpu.spark import MockIoMesh, Spark
from openr_tpu.types import (
    AdjacencyDatabase,
    InterfaceInfo,
    KeyValueRequestType,
    KvStoreSyncEvent,
    NeighborEvent,
    NeighborEventType,
    SparkNeighState,
    adj_key,
)
from tests.conftest import run_async

FAST = SparkConfig(
    hello_time_s=0.08,
    fastinit_hello_time_ms=20,
    keepalive_time_s=0.05,
    hold_time_s=0.3,
    graceful_restart_time_s=0.5,
    handshake_time_ms=40,
    min_packets_per_sec=0,  # no rate limiting in fast tests
)


class SparkNode:
    def __init__(self, mesh: MockIoMesh, name: str, config=FAST):
        self.name = name
        self.neighbor_q = ReplicateQueue(f"{name}.neighborUpdates")
        self.events = self.neighbor_q.get_reader("test")
        self.spark = Spark(
            name, config, mesh.provider(name), self.neighbor_q
        )

    async def start(self, *ifaces: str):
        for i in ifaces:
            self.spark.add_interface(i)
        await self.spark.start()

    async def stop(self):
        self.neighbor_q.close()
        await self.spark.stop()

    async def next_event(self, timeout=5.0) -> NeighborEvent:
        async def get():
            while True:
                item = await self.events.get()
                if isinstance(item, NeighborEvent):
                    return item

        return await asyncio.wait_for(get(), timeout)

    async def expect(self, event_type, node=None, timeout=5.0) -> NeighborEvent:
        async def hunt():
            while True:
                ev = await self.next_event()
                if ev.event_type == event_type and (
                    node is None or ev.node_name == node
                ):
                    return ev

        return await asyncio.wait_for(hunt(), timeout)


class TestSparkTwoNode:
    @run_async
    async def test_neighbor_up_both_sides(self):
        mesh = MockIoMesh()
        a, b = SparkNode(mesh, "a"), SparkNode(mesh, "b")
        mesh.connect("a", "if-ab", "b", "if-ba")
        await a.start("if-ab")
        await b.start("if-ba")
        try:
            ev_a = await a.expect(NeighborEventType.NEIGHBOR_UP, "b")
            ev_b = await b.expect(NeighborEventType.NEIGHBOR_UP, "a")
            assert ev_a.if_name == "if-ab"
            assert ev_b.if_name == "if-ba"
            nbs = await a.spark.get_neighbors()
            assert nbs[0].state == SparkNeighState.ESTABLISHED
        finally:
            await a.stop()
            await b.stop()

    @run_async
    async def test_neighbor_down_on_partition(self):
        mesh = MockIoMesh()
        a, b = SparkNode(mesh, "a"), SparkNode(mesh, "b")
        mesh.connect("a", "if-ab", "b", "if-ba")
        await a.start("if-ab")
        await b.start("if-ba")
        try:
            await a.expect(NeighborEventType.NEIGHBOR_UP, "b")
            mesh.partition("a", "b")
            ev = await a.expect(NeighborEventType.NEIGHBOR_DOWN, "b", timeout=5)
            assert ev.node_name == "b"
        finally:
            await a.stop()
            await b.stop()

    @run_async
    async def test_reestablish_after_heal(self):
        mesh = MockIoMesh()
        a, b = SparkNode(mesh, "a"), SparkNode(mesh, "b")
        mesh.connect("a", "if-ab", "b", "if-ba")
        await a.start("if-ab")
        await b.start("if-ba")
        try:
            await a.expect(NeighborEventType.NEIGHBOR_UP, "b")
            mesh.partition("a", "b")
            await a.expect(NeighborEventType.NEIGHBOR_DOWN, "b")
            await b.expect(NeighborEventType.NEIGHBOR_DOWN, "a")
            mesh.heal("a", "b")
            await a.expect(NeighborEventType.NEIGHBOR_UP, "b", timeout=8)
        finally:
            await a.stop()
            await b.stop()

    @run_async
    async def test_graceful_restart_holds_adjacency(self):
        mesh = MockIoMesh()
        a, b = SparkNode(mesh, "a"), SparkNode(mesh, "b")
        mesh.connect("a", "if-ab", "b", "if-ba")
        await a.start("if-ab")
        await b.start("if-ba")
        try:
            await a.expect(NeighborEventType.NEIGHBOR_UP, "b")
            # b announces restart, then comes back
            await b.spark.send_restarting_hellos()
            await a.expect(NeighborEventType.NEIGHBOR_RESTARTING, "b")
            # b's fresh hellos (it kept running) re-negotiate
            await a.expect(NeighborEventType.NEIGHBOR_RESTARTED, "b", timeout=8)
        finally:
            await a.stop()
            await b.stop()

    @run_async
    async def test_gr_timeout_downs_neighbor(self):
        mesh = MockIoMesh()
        cfg = SparkConfig(
            hello_time_s=0.08,
            fastinit_hello_time_ms=20,
            keepalive_time_s=0.05,
            hold_time_s=0.3,
            graceful_restart_time_s=0.3,
            handshake_time_ms=40,
            min_packets_per_sec=0,
        )
        a, b = SparkNode(mesh, "a", cfg), SparkNode(mesh, "b", cfg)
        mesh.connect("a", "if-ab", "b", "if-ba")
        await a.start("if-ab")
        await b.start("if-ba")
        try:
            await a.expect(NeighborEventType.NEIGHBOR_UP, "b")
            await b.spark.send_restarting_hellos()
            await b.stop()  # b really goes away
            await a.expect(NeighborEventType.NEIGHBOR_RESTARTING, "b")
            mesh.partition("a", "b")
            await a.expect(NeighborEventType.NEIGHBOR_DOWN, "b", timeout=5)
        finally:
            await a.stop()

    @run_async
    async def test_rtt_measured(self):
        mesh = MockIoMesh()
        a, b = SparkNode(mesh, "a"), SparkNode(mesh, "b")
        mesh.connect("a", "if-ab", "b", "if-ba", latency_s=0.02)
        await a.start("if-ab")
        await b.start("if-ba")
        try:
            await a.expect(NeighborEventType.NEIGHBOR_UP, "b", timeout=8)
            await wait_until(
                lambda: a.spark.neighbors[("if-ab", "b")].rtt_us > 0,
                timeout_s=5,
            )
            rtt = a.spark.neighbors[("if-ab", "b")].rtt_us
            # one-way 20ms -> rtt ~40ms
            assert 20_000 < rtt < 200_000, rtt
        finally:
            await a.stop()
            await b.stop()


class TestSparkHubSpoke:
    @run_async
    async def test_three_node_star(self):
        """hub h with two spokes s1, s2 on separate interfaces."""
        mesh = MockIoMesh()
        h = SparkNode(mesh, "h")
        s1, s2 = SparkNode(mesh, "s1"), SparkNode(mesh, "s2")
        mesh.connect("h", "if-1", "s1", "if-h")
        mesh.connect("h", "if-2", "s2", "if-h")
        await h.start("if-1", "if-2")
        await s1.start("if-h")
        await s2.start("if-h")
        try:
            up = set()
            while up != {"s1", "s2"}:
                ev = await h.expect(NeighborEventType.NEIGHBOR_UP)
                up.add(ev.node_name)
            assert {
                (nb.if_name, nb.node_name) for nb in await h.spark.get_neighbors()
            } == {("if-1", "s1"), ("if-2", "s2")}
            # spokes do NOT see each other (separate segments)
            assert all(
                nb.node_name == "h" for nb in await s1.spark.get_neighbors()
            )
        finally:
            await h.stop()
            await s1.stop()
            await s2.stop()


class TestLinkMonitor:
    def _make(self, kvstore_events=True):
        neighbor_q = ReplicateQueue("neighborUpdates")
        kvstore_ev_q = ReplicateQueue("kvStoreEvents")
        peer_q = ReplicateQueue("peerUpdates")
        kv_req_q = ReplicateQueue("kvRequests")
        lm = LinkMonitor(
            "node1",
            LinkMonitorConfig(
                linkflap_initial_backoff_ms=1, linkflap_max_backoff_ms=8
            ),
            neighbor_q.get_reader(),
            kvstore_ev_q.get_reader() if kvstore_events else None,
            peer_q,
            kv_req_q,
            advertise_throttle_s=0.001,
        )
        return lm, neighbor_q, kvstore_ev_q, peer_q.get_reader("t"), kv_req_q.get_reader("t")

    @staticmethod
    def neighbor_up(node="nbr", rtt_us=500, area="0"):
        return NeighborEvent(
            event_type=NeighborEventType.NEIGHBOR_UP,
            node_name=node,
            if_name=f"if-{node}",
            area=area,
            ctrl_port=1234,
            rtt_us=rtt_us,
        )

    @run_async
    async def test_neighbor_up_adds_peer_and_advertises_after_sync(self):
        lm, nq, kvq, peers, reqs = self._make()
        await lm.start()
        try:
            nq.push(self.neighbor_up())
            peer_ev = await asyncio.wait_for(peers.get(), 2)
            assert "nbr" in peer_ev["0"].peers_to_add
            # not announced yet: initial sync with peer pending
            await asyncio.sleep(0.05)
            assert reqs.size() == 0
            kvq.push(KvStoreSyncEvent("nbr", "0"))
            req = await asyncio.wait_for(reqs.get(), 2)
            assert req.request_type == KeyValueRequestType.PERSIST
            assert req.key == adj_key("node1")
            db = deserialize(req.value, AdjacencyDatabase)
            assert db.adjacencies[0].other_node_name == "nbr"
            assert db.adjacencies[0].metric == get_rtt_metric(500)
        finally:
            await lm.stop()

    @run_async
    async def test_neighbor_down_removes_peer_and_readvertises(self):
        lm, nq, kvq, peers, reqs = self._make()
        await lm.start()
        try:
            nq.push(self.neighbor_up())
            await asyncio.wait_for(peers.get(), 2)
            kvq.push(KvStoreSyncEvent("nbr", "0"))
            await asyncio.wait_for(reqs.get(), 2)
            nq.push(
                NeighborEvent(
                    event_type=NeighborEventType.NEIGHBOR_DOWN,
                    node_name="nbr",
                    if_name="if-nbr",
                    area="0",
                )
            )
            peer_ev = await asyncio.wait_for(peers.get(), 2)
            assert "nbr" in peer_ev["0"].peers_to_del
            req = await asyncio.wait_for(reqs.get(), 2)
            db = deserialize(req.value, AdjacencyDatabase)
            assert db.adjacencies == ()
        finally:
            await lm.stop()

    @run_async
    async def test_rtt_change_updates_metric(self):
        lm, nq, kvq, peers, reqs = self._make()
        await lm.start()
        try:
            nq.push(self.neighbor_up(rtt_us=500))
            await asyncio.wait_for(peers.get(), 2)
            kvq.push(KvStoreSyncEvent("nbr", "0"))
            await asyncio.wait_for(reqs.get(), 2)
            nq.push(
                NeighborEvent(
                    event_type=NeighborEventType.NEIGHBOR_RTT_CHANGE,
                    node_name="nbr",
                    if_name="if-nbr",
                    area="0",
                    rtt_us=5000,
                )
            )
            req = await asyncio.wait_for(reqs.get(), 2)
            db = deserialize(req.value, AdjacencyDatabase)
            assert db.adjacencies[0].metric == get_rtt_metric(5000)
        finally:
            await lm.stop()

    @run_async
    async def test_node_overload_advertised(self):
        lm, nq, kvq, peers, reqs = self._make()
        await lm.start()
        try:
            nq.push(self.neighbor_up())
            await asyncio.wait_for(peers.get(), 2)
            kvq.push(KvStoreSyncEvent("nbr", "0"))
            await asyncio.wait_for(reqs.get(), 2)
            await lm.set_node_overload(True)
            req = await asyncio.wait_for(reqs.get(), 2)
            db = deserialize(req.value, AdjacencyDatabase)
            assert db.is_overloaded
        finally:
            await lm.stop()

    @run_async
    async def test_link_metric_override(self):
        lm, nq, kvq, peers, reqs = self._make()
        await lm.start()
        try:
            nq.push(self.neighbor_up())
            await asyncio.wait_for(peers.get(), 2)
            kvq.push(KvStoreSyncEvent("nbr", "0"))
            await asyncio.wait_for(reqs.get(), 2)
            await lm.set_link_metric("if-nbr", 777)
            req = await asyncio.wait_for(reqs.get(), 2)
            db = deserialize(req.value, AdjacencyDatabase)
            assert db.adjacencies[0].metric == 777
        finally:
            await lm.stop()

    @run_async
    async def test_state_persistence(self, tmp_path=None):
        import tempfile

        from openr_tpu.runtime.persistent_store import PersistentStore

        with tempfile.TemporaryDirectory() as d:
            store = PersistentStore(f"{d}/state.bin")
            lm, nq, kvq, peers, reqs = self._make()
            lm._store = store
            await lm.start()
            await lm.set_node_overload(True)
            await lm.stop()
            store.close()

            store2 = PersistentStore(f"{d}/state.bin")
            lm2, *_ = self._make()
            lm2._store = store2
            await lm2.start()
            try:
                assert lm2.state.is_overloaded
            finally:
                await lm2.stop()
                store2.close()

    @run_async
    async def test_interface_flap_backoff(self):
        lm, nq, kvq, peers, reqs = self._make()
        iface_q = ReplicateQueue("interfaceUpdates")
        iface_reader = iface_q.get_reader("t")
        lm._interface_q = iface_q
        await lm.start()
        try:
            up = InterfaceInfo(if_name="eth0", is_up=True, networks=("10.0.0.1/32",))
            down = InterfaceInfo(if_name="eth0", is_up=False)
            lm.update_interface(down)
            lm.update_interface(up)  # first flap: 1ms backoff
            await wait_until(
                lambda: any(
                    i.if_name == "eth0"
                    for db in self._drain(iface_reader)
                    for i in db.interfaces
                )
                or lm.interfaces["eth0"].active,
                timeout_s=2,
            )
            assert lm.interfaces["eth0"].active
        finally:
            await lm.stop()

    @staticmethod
    def _drain(reader):
        out = []
        while reader.size():
            ok, item = reader.try_get()
            if ok:
                out.append(item)
        return out


class TestSparkRobustness:
    """Malformed/hostile input must not wedge the FSM (the reference
    keeps an explicit fuzzer seam — Spark.h:84-85 setThrowParserErrors;
    here the parse boundary is the serde deserialize in IoProvider and
    the per-message handlers' exception isolation)."""

    @run_async
    async def test_garbage_datagrams_dont_break_discovery(self):
        """Blast raw garbage at a live UDP provider port while two real
        sparks establish — discovery must still converge."""
        import socket as _socket

        from openr_tpu.spark.io_provider import UdpIoProvider

        io_a = UdpIoProvider(0)
        io_b = UdpIoProvider(0)
        addr_a = await io_a.add_interface("if0", "127.0.0.1", None)
        addr_b = await io_b.add_interface("if0", "127.0.0.1", None)
        io_a.set_peers("if0", [addr_b])
        io_b.set_peers("if0", [addr_a])

        qa = ReplicateQueue("a.nbr")
        events = qa.get_reader("test")
        a = Spark("a", FAST, io_a, qa)
        qb = ReplicateQueue("b.nbr")
        b = Spark("b", FAST, io_b, qb)
        a.add_interface("if0")
        b.add_interface("if0")
        await a.start()
        await b.start()
        try:
            # hostile traffic straight at a's socket: junk bytes, empty
            # JSON, truncated frames
            s = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
            for payload in (b"\x00\xff" * 50, b"{}", b'{"hello":', b""):
                for _ in range(20):
                    s.sendto(payload, addr_a)
            s.close()

            async def established():
                while True:
                    ev = await events.get()
                    if (
                        isinstance(ev, NeighborEvent)
                        and ev.event_type == NeighborEventType.NEIGHBOR_UP
                    ):
                        return ev

            ev = await asyncio.wait_for(established(), 10)
            assert ev.node_name == "b"
        finally:
            qa.close()
            qb.close()
            await a.stop()
            await b.stop()

    @run_async
    async def test_hostile_field_values_are_isolated(self):
        """Well-formed packets with absurd field values (negative seq,
        empty node name, unknown-neighbor heartbeat) are dropped or
        ignored without killing the recv loop."""
        from openr_tpu.types import (
            SparkHeartbeatMsg,
            SparkHelloMsg,
            SparkPacket,
        )

        mesh = MockIoMesh()
        a = SparkNode(mesh, "a")
        b = SparkNode(mesh, "b")
        mesh.connect("a", "if-ab", "b", "if-ba")
        evil = mesh.provider("evil")
        mesh.connect("evil", "if-ea", "a", "if-ab")
        await a.start("if-ab")
        await b.start("if-ba")
        try:
            await evil.send(
                "if-ea",
                SparkPacket(
                    heartbeat=SparkHeartbeatMsg(
                        node_name="ghost", seq_num=-5
                    )
                ),
            )
            await evil.send(
                "if-ea",
                SparkPacket(
                    hello=SparkHelloMsg(
                        domain_name="", node_name="", if_name="",
                        seq_num=-1, sent_ts_us=-99,
                    )
                ),
            )
            await wait_until(
                lambda: a.spark.neighbors.get(("if-ab", "b")) is not None
                and a.spark.neighbors[("if-ab", "b")].state
                == SparkNeighState.ESTABLISHED,
                timeout_s=10,
            )
            # the hostile senders created NO neighbor state: a nameless
            # hello would otherwise live forever (WARM sessions have no
            # hold timer) and 'ghost' never completed the FSM handshake
            assert ("if-ab", "") not in a.spark.neighbors
            assert ("if-ab", "ghost") not in a.spark.neighbors
            assert set(a.spark.neighbors) == {("if-ab", "b")}
        finally:
            await a.stop()
            await b.stop()

    @run_async
    async def test_spoofed_names_are_swept(self):
        """Distinct spoofed node_names create transient WARM entries at
        most: the stale-session sweep reaps pre-ESTABLISHED state that
        stops talking, while the real neighbor survives."""
        from openr_tpu.kvstore.wrapper import wait_until
        from openr_tpu.types import SparkHelloMsg, SparkPacket

        mesh = MockIoMesh()
        a = SparkNode(mesh, "a")
        b = SparkNode(mesh, "b")
        mesh.connect("a", "if-ab", "b", "if-ba")
        evil = mesh.provider("evil")
        mesh.connect("evil", "if-ea", "a", "if-ab")
        await a.start("if-ab")
        await b.start("if-ba")
        try:
            for i in range(50):
                await evil.send(
                    "if-ea",
                    SparkPacket(
                        hello=SparkHelloMsg(
                            domain_name="", node_name=f"spoof-{i}",
                            if_name="x", seq_num=1, sent_ts_us=1,
                        )
                    ),
                )
            await wait_until(
                lambda: a.spark.neighbors.get(("if-ab", "b")) is not None
                and a.spark.neighbors[("if-ab", "b")].state
                == SparkNeighState.ESTABLISHED,
                timeout_s=10,
            )
            # ttl = max(hold 0.3s, 3*hello 0.24s); sweep rides the hello
            # cadence — all spoofed WARM entries must be gone shortly
            await wait_until(
                lambda: set(a.spark.neighbors) == {("if-ab", "b")},
                timeout_s=5,
            )
            assert (
                a.spark.neighbors[("if-ab", "b")].state
                == SparkNeighState.ESTABLISHED
            )
        finally:
            await a.stop()
            await b.stop()


class TestSoftDrain:
    """Node/interface metric increments (ref setNodeInterfaceMetric-
    Increment; LinkMonitor.cpp:1013 applies them at advertisement).

    Borrows TestLinkMonitor's fixtures without subclassing it — pytest
    would re-collect every inherited test method as a duplicate."""

    _make = TestLinkMonitor._make
    neighbor_up = staticmethod(TestLinkMonitor.neighbor_up)

    @run_async
    async def test_increments_inflate_advertised_metrics(self):
        import pytest

        lm, nq, kvq, peers, reqs = self._make()
        await lm.start()
        try:
            nq.push(self.neighbor_up())
            await asyncio.wait_for(peers.get(), 2)
            kvq.push(KvStoreSyncEvent("nbr", "0"))
            await asyncio.wait_for(reqs.get(), 2)
            base = lm.build_adjacency_database("0").adjacencies[0].metric

            await lm.set_node_metric_increment(50)
            db = lm.build_adjacency_database("0")
            assert db.adjacencies[0].metric == base + 50
            assert db.node_metric_increment == 50

            await lm.set_link_metric_increment("if-nbr", 7)
            assert (
                lm.build_adjacency_database("0").adjacencies[0].metric
                == base + 57
            )

            # unset both: back to the measured metric
            await lm.set_node_metric_increment(0)
            await lm.set_link_metric_increment("if-nbr", 0)
            assert (
                lm.build_adjacency_database("0").adjacencies[0].metric == base
            )

            with pytest.raises(ValueError):
                await lm.set_node_metric_increment(-5)
        finally:
            await lm.stop()


class TestAreaAdmission:
    """resolve_area returning None must REFUSE the neighbor — no state,
    no adjacency under a phantom area (review finding: the matchers
    previously failed open to area '')."""

    @run_async
    async def test_unmatched_neighbor_refused(self):
        from openr_tpu.runtime.counters import counters

        mesh = MockIoMesh()
        a, b = SparkNode(mesh, "a"), SparkNode(mesh, "b")
        # a admits only spine-* nodes; b has no restrictions
        a.spark._resolve_area = (
            lambda node, iface: "0" if node.startswith("spine-") else None
        )
        mesh.connect("a", "if-ab", "b", "if-ba")
        before = counters.get_counter("spark.neighbor.no_area_match") or 0
        await a.start("if-ab")
        await b.start("if-ba")
        try:
            # b keeps helloing; a must never form state for it
            await asyncio.sleep(0.6)
            assert await a.spark.get_neighbors() == []
            assert (
                counters.get_counter("spark.neighbor.no_area_match") or 0
            ) > before
            # b sees a's hellos but never completes (a won't handshake)
            nbs = await b.spark.get_neighbors()
            assert all(
                nb.state != SparkNeighState.ESTABLISHED for nb in nbs
            )
        finally:
            await a.stop()
            await b.stop()
