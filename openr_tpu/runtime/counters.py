"""Process-wide counters/stats fabric.

Role of fb303 (`fb303::fbData->addStatValue/setCounter`) which the
reference uses everywhere (e.g. decision.spf_ms LinkState.cpp:909,
kvstore thrift counters KvStore.cpp:3263). Flat singleton registry with
counters (set/increment) and stats (windowed sum/count/avg), exported via
the ctrl API and the monitor module.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional


class _Stat:
    __slots__ = ("samples",)

    def __init__(self):
        # (ts, value) ring: 4096 most-recent samples; windowed() filters by age
        self.samples: collections.deque = collections.deque(maxlen=4096)

    def add(self, value: float) -> None:
        self.samples.append((time.monotonic(), value))

    def windowed(self, window_s: float = 60.0) -> dict:
        cutoff = time.monotonic() - window_s
        vals = [v for ts, v in self.samples if ts >= cutoff]
        n = len(vals)
        return {
            "count": n,
            "sum": sum(vals),
            "avg": (sum(vals) / n) if n else 0.0,
            "max": max(vals) if vals else 0.0,
        }

    def multi_windowed(self, windows: tuple) -> dict:
        """One pass over the ring bucketing every sample into each
        window it falls in (60s samples are a subset of 600s etc.).
        A window is marked truncated when the ring's eviction horizon
        is newer than its cutoff — the ring holds the 4096 most-recent
        samples, so a high-rate stat cannot honor long windows and must
        SAY so rather than silently undercount."""
        return _aggregate_windows(
            list(self.samples), self.samples.maxlen, windows
        )


def _aggregate_windows(samples: list, maxlen: int, windows: tuple) -> dict:
    now = time.monotonic()
    # ascending cutoff = largest window first; once a sample is too
    # old for a window it is too old for every smaller one -> break
    cutoffs = sorted((now - w, w) for w in windows)
    acc = {w: {"count": 0, "sum": 0.0, "max": None} for _, w in cutoffs}
    for ts, v in samples:
        for cutoff, w in cutoffs:
            if ts < cutoff:
                break
            a = acc[w]
            a["count"] += 1
            a["sum"] += v
            if a["max"] is None or v > a["max"]:
                a["max"] = v
    full = len(samples) == maxlen
    oldest = samples[0][0] if samples else now
    out = {}
    for cutoff, w in cutoffs:
        a = acc[w]
        out[str(int(w))] = {
            "count": a["count"],
            "sum": a["sum"],
            # empty window reports 0.0 (matches windowed()); a window
            # of negative samples reports its true maximum
            "max": a["max"] if a["max"] is not None else 0.0,
            "avg": (a["sum"] / a["count"]) if a["count"] else 0.0,
            "truncated": full and oldest > cutoff,
        }
    return out


class CounterRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._stats: dict[str, _Stat] = {}

    def set_counter(self, key: str, value: float) -> None:
        with self._lock:
            self._counters[key] = value

    def increment(self, key: str, delta: float = 1) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + delta

    def add_stat_value(self, key: str, value: float) -> None:
        with self._lock:
            st = self._stats.get(key)
            if st is None:
                st = self._stats[key] = _Stat()
            st.add(value)

    def get_counter(self, key: str) -> Optional[float]:
        return self._counters.get(key)

    def get_statistics(
        self, prefix: str = "", windows: tuple = (60.0, 600.0, 3600.0)
    ) -> dict[str, dict]:
        """fb303-style multi-window stat view (ref breeze monitor
        statistics): per stat key, count/sum/avg/max over each window.
        Only the sample-ring snapshot happens under the registry lock —
        the aggregation runs outside it, so a statistics poll can't
        stall hot-path add_stat_value/increment calls mid-SPF."""
        with self._lock:
            snap = {
                k: (list(st.samples), st.samples.maxlen)
                for k, st in self._stats.items()
                if k.startswith(prefix)
            }
        return {
            k: _aggregate_windows(samples, maxlen, windows)
            for k, (samples, maxlen) in snap.items()
        }

    def get_counters(self, prefix: str = "") -> dict[str, float]:
        with self._lock:
            out = {k: v for k, v in self._counters.items() if k.startswith(prefix)}
            for k, st in self._stats.items():
                if k.startswith(prefix):
                    w = st.windowed()
                    out[f"{k}.avg.60"] = w["avg"]
                    out[f"{k}.count.60"] = w["count"]
                    out[f"{k}.sum.60"] = w["sum"]
            return out

    def clear(self) -> None:
        with self._lock:
            self._counters.clear()
            self._stats.clear()


# the process-wide instance (role of fb303::fbData)
counters = CounterRegistry()
