"""Entry point: `python -m tools.lint [--all] [--checker NAME ...]`.

Runs the five project checkers over `openr_tpu/` (exit 1 on any
unsuppressed finding); `--all` additionally shells out to ruff when it
is installed (the CI lint lane installs it; a dev box without ruff
gets a skip note, not a failure, since the container image is fixed).
"""

from __future__ import annotations

import argparse
import shutil
import subprocess
import sys
from pathlib import Path

from tools.lint import affinity, blocking, excepts, metric_names, purity
from tools.lint.core import (
    DEFAULT_ALLOWLIST,
    REPO_ROOT,
    Allowlist,
    Project,
    apply_suppressions,
)

CHECKERS = {
    "affinity": affinity.run,
    "purity": purity.run,
    "blocking": blocking.run,
    "excepts": excepts.run,
    "metric-names": metric_names.run,
}


def _run_ruff() -> int | None:
    """Exit code, or None when ruff isn't installed (skip, not fail)."""
    if shutil.which("ruff") is None:
        print(
            "tools.lint: ruff not installed — skipping ruff lane "
            "(CI installs it; config lives in pyproject.toml)"
        )
        return None
    proc = subprocess.run(
        ["ruff", "check", "openr_tpu/", "tools/", "tests/"],
        cwd=REPO_ROOT,
    )
    return proc.returncode


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.lint")
    ap.add_argument(
        "--checker", action="append", choices=sorted(CHECKERS),
        help="run only the named checker(s); default: all five",
    )
    ap.add_argument(
        "--all", action="store_true",
        help="also run ruff (the full CI lint lane)",
    )
    ap.add_argument(
        "--allowlist", type=Path, default=DEFAULT_ALLOWLIST,
        help="allowlist JSON path (default tools/lint/allowlist.json)",
    )
    ap.add_argument(
        "--package", default="openr_tpu",
        help="package directory to scan (default openr_tpu)",
    )
    args = ap.parse_args(argv)

    project = Project(REPO_ROOT, [args.package])
    allowlist = Allowlist.load(args.allowlist)

    failures = 0
    for err in project.parse_errors:
        print(f"tools.lint: {err}", file=sys.stderr)
        failures += 1
    for err in allowlist.errors:
        print(f"tools.lint: {err}", file=sys.stderr)
        failures += 1

    selected = args.checker or sorted(CHECKERS)
    findings = []
    for name in selected:
        findings.extend(CHECKERS[name](project))
    # a pragma without a reason is itself a finding
    for sf in project.files:
        findings.extend(sf.pragma_errors)

    remaining = apply_suppressions(findings, project, allowlist)
    remaining.sort(key=lambda f: (f.path, f.line, f.code))
    for fd in remaining:
        print(fd.render(), file=sys.stderr)
    failures += len(remaining)

    # stale allowlist entries rot into blanket permission — warn loudly
    # (only when every checker ran; a partial run can't prove staleness)
    if not args.checker:
        for key in allowlist.unused():
            print(f"tools.lint: WARNING unused allowlist entry: {key}")

    ruff_ran = False
    if args.all:
        rc = _run_ruff()
        ruff_ran = rc is not None
        if ruff_ran and rc != 0:
            failures += 1

    checked = "+".join(selected) + ("+ruff" if ruff_ran else "")
    if failures:
        print(
            f"tools.lint: FAIL — {failures} problem(s) [{checked}] "
            f"(suppress with `# lint: allow(<code>) <reason>` or an "
            f"allowlist entry; see docs/StaticAnalysis.md)",
            file=sys.stderr,
        )
        return 1
    print(
        f"tools.lint: OK — {len(project.files)} files clean [{checked}]"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
