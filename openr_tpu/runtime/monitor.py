"""Monitor + Watchdog actors — observability and self-healing.

Monitor (role of openr/monitor/MonitorBase.{h,cpp} :32-80, Monitor,
LogSample, SystemMetrics): consumes the log-sample queue of structured
JSON event logs, retains the last N, and exports process CPU/memory/uptime
counters into the counter fabric every interval.

Watchdog (role of openr/watchdog/Watchdog.{h,cpp} :20): every interval it
checks each registered actor's health timestamp — staleness beyond
thread_timeout fires the crash handler (the reference aborts the whole
process for supervisor restart, ref fireCrash) — enforces the memory
ceiling, and exports per-queue depth counters (ref Watchdog.h:28-51).
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import os
import resource
import shutil
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from openr_tpu.config import MonitorConfig, WatchdogConfig
from openr_tpu.messaging import ReplicateQueue, RQueue
from openr_tpu.runtime import device_stats
from openr_tpu.runtime.actor import Actor
from openr_tpu.runtime.counters import counters
from openr_tpu.runtime.overload import (
    get_controller as get_overload_controller,
)
from openr_tpu.runtime.perf_ledger import configure as configure_perf_ledger
from openr_tpu.runtime.tracing import tracer

log = logging.getLogger(__name__)

# ru_maxrss units differ by platform: Linux reports KB, macOS bytes
_RSS_DIVISOR = 1024.0 * 1024.0 if sys.platform == "darwin" else 1024.0
_PAGE_SIZE = resource.getpagesize()


def rss_mb() -> float:
    """PEAK resident set (high-water mark) — ru_maxrss never decreases.
    Right for the Watchdog memory ceiling; wrong for a live gauge."""
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / _RSS_DIVISOR


def current_rss_mb() -> float:
    """Current resident set from /proc/self/statm field 2 (resident
    pages); falls back to the peak where procfs is unavailable."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * _PAGE_SIZE / (1024.0 * 1024.0)
    except (OSError, IndexError, ValueError):
        return rss_mb()


@dataclass
class LogSample:
    """Structured event log (ref openr/monitor/LogSample.{h,cpp})."""

    event: str
    node_name: str = ""
    ts_ms: int = field(default_factory=lambda: int(time.time() * 1000))
    values: dict = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(
            {
                "event": self.event,
                "node_name": self.node_name,
                "ts_ms": self.ts_ms,
                **self.values,
            },
            sort_keys=True,
        )


_SLO_STATE_LEVEL = {"ok": 0, "fast_burn": 1, "sustained_burn": 2}


class _SloTrack:
    """Per-SLO burn-rate state machine bookkeeping."""

    __slots__ = (
        "name",
        "spec",
        "state",
        "samples",
        "value",
        "fast_burn",
        "slow_burn",
        "alerts",
        "last_transition_ms",
        "_gauge_since",
        "_prev_counter",
        "baseline",
        "live",
    )

    def __init__(self, name: str, spec: dict):
        self.name = name
        self.spec = spec
        self.state = "ok"
        # (monotonic ts, breached) per evaluation tick; pruned to the
        # slow window — the fast window is a suffix of the same deque
        self.samples: collections.deque = collections.deque()
        self.value = 0.0
        self.fast_burn = 0.0
        self.slow_burn = 0.0
        self.alerts = 0
        self.last_transition_ms = 0
        self._gauge_since: Optional[float] = None
        self._prev_counter: Optional[float] = None
        # baseline_drift bookkeeping: the ledger quantile and the live
        # window quantile behind the last measured ratio
        self.baseline: Optional[float] = None
        self.live = 0.0


class SloEngine:
    """Declarative SLO table → multi-window burn-rate state machines.

    Each spec in MonitorConfig.slos names a counter-fabric source, a
    kind, and a threshold. Every monitor tick measures the source,
    records whether it breached, and tracks the breach FRACTION over a
    fast and a slow window (the SRE-workbook multi-window burn-rate
    pattern): a fast-window fraction ≥ burn_threshold raises the alert
    (pages fast on hard outages), and a slow-window fraction ≥ the same
    threshold escalates to sustained_burn (distinguishes a blip from a
    budget-eating trend). De-assert needs the fast window to drain to
    half the threshold AND a clean current tick — 2× hysteresis so a
    flapping source can't strobe the alert.

    Kinds:
      stat           — windowed quantile (default p99) of a stat series
                       vs threshold; no samples in window = no breach
      counter_delta  — increase of a monotonic counter since the last
                       tick > threshold (threshold 0 = any increase)
      gauge_duration — gauge continuously nonzero for ≥ threshold
                       seconds
      baseline_drift — live window quantile of `source` divided by the
                       perf-ledger baseline quantile (threshold = max
                       allowed ratio, e.g. 1.5). Never breaches without
                       a stored baseline, with fewer than `min_count`
                       live samples in the window, or inside the
                       `warmup_s` cold-start exclusion (a restarting
                       node's compile-heavy first solves are not drift)
    """

    def __init__(self, node_name: str, cfg: MonitorConfig):
        self.node_name = node_name
        self.cfg = cfg
        self._started = time.monotonic()
        self._tracks = {
            name: _SloTrack(name, dict(spec))
            for name, spec in (cfg.slos or {}).items()
        }

    def _windows(self, spec: dict) -> tuple:
        fast = float(spec.get("fast_window_s", self.cfg.slo_fast_window_s))
        slow = float(spec.get("slow_window_s", self.cfg.slo_slow_window_s))
        return fast, max(slow, fast)

    def _measure(self, track: _SloTrack, now: float) -> tuple:
        """→ (value, breached) for one SLO at this tick."""
        spec = track.spec
        kind = spec.get("kind", "stat")
        source = spec["source"]
        threshold = float(spec["threshold"])
        if kind == "stat":
            fast_s, _ = self._windows(spec)
            win = counters.get_statistics(
                source, windows=(max(fast_s, 1.0),)
            ).get(source, {})
            agg = next(iter(win.values()), {})
            value = float(agg.get(spec.get("quantile", "p99"), 0.0))
            return value, bool(agg.get("count", 0)) and value > threshold
        if kind == "baseline_drift":
            from openr_tpu.runtime.perf_ledger import get_ledger

            fast_s, _ = self._windows(spec)
            quantile = spec.get("quantile", "p95")
            win = counters.get_statistics(
                source, windows=(max(fast_s, 1.0),)
            ).get(source, {})
            agg = next(iter(win.values()), {})
            track.live = float(agg.get(quantile, 0.0))
            track.baseline = get_ledger().baseline(
                spec.get("baseline_kernel", "solve"),
                spec.get("baseline_metric", "device_ms"),
                signature=spec.get("baseline_signature", "live"),
                variant=spec.get("baseline_variant", "live"),
                quantile=quantile,
            )
            if (
                track.baseline is None
                or track.baseline <= 0.0
                # thin windows produce garbage quantiles
                or int(agg.get("count", 0)) < int(spec.get("min_count", 3))
                # cold-start exclusion: a fresh engine's first window is
                # full of compile-heavy solves, not regressions
                or now - self._started < float(spec.get("warmup_s", fast_s))
            ):
                return 0.0, False
            value = track.live / track.baseline
            return value, value > threshold
        if kind == "counter_delta":
            cur = float(counters.get_counter(source) or 0.0)
            prev = track._prev_counter
            track._prev_counter = cur
            # first observation establishes the baseline — a counter
            # that predates the engine must not fire retroactively
            value = 0.0 if prev is None else max(0.0, cur - prev)
            return value, value > threshold
        # gauge_duration
        gauge = float(counters.get_counter(source) or 0.0)
        if gauge > 0.0:
            if track._gauge_since is None:
                track._gauge_since = now
            value = now - track._gauge_since
            return value, value >= threshold
        track._gauge_since = None
        # a cleared gauge never breaches — even at threshold 0, where
        # value >= threshold would hold vacuously forever
        return 0.0, False

    def evaluate(self) -> list[dict]:
        """One engine tick over every SLO; exports the per-SLO gauges
        and returns ONLY newly-raised burn alerts (ok → fast_burn
        transitions) — escalation and recovery are gauge transitions,
        not pages."""
        now = time.monotonic()
        alerts = []
        for name, track in self._tracks.items():
            value, breached = self._measure(track, now)
            track.value = value
            fast_s, slow_s = self._windows(track.spec)
            track.samples.append((now, breached))
            while track.samples and track.samples[0][0] < now - slow_s:
                track.samples.popleft()
            fast_cut = now - fast_s
            fast = [b for ts, b in track.samples if ts >= fast_cut]
            track.fast_burn = sum(fast) / len(fast) if fast else 0.0
            track.slow_burn = sum(b for _, b in track.samples) / len(
                track.samples
            )
            burn_at = float(
                track.spec.get("burn_threshold", self.cfg.slo_burn_threshold)
            )
            prev_state = track.state
            if track.state == "ok":
                if fast and track.fast_burn >= burn_at:
                    track.state = "fast_burn"
            elif track.fast_burn <= burn_at / 2.0 and not breached:
                track.state = "ok"
            elif track.state == "fast_burn" and track.slow_burn >= burn_at:
                track.state = "sustained_burn"
            if track.state != prev_state:
                track.last_transition_ms = int(time.time() * 1000)
                if prev_state == "ok":
                    track.alerts += 1
                    counters.increment(f"monitor.slo.{name}.alerts")
                    alert = {
                        "slo": name,
                        "kind": track.spec.get("kind", "stat"),
                        "state": track.state,
                        "source": track.spec["source"],
                        "threshold": float(track.spec["threshold"]),
                        "value": round(value, 3),
                        "fast_burn": round(track.fast_burn, 3),
                        "slow_burn": round(track.slow_burn, 3),
                    }
                    if track.spec.get("kind") == "baseline_drift":
                        alert["baseline"] = (
                            round(track.baseline, 3)
                            if track.baseline is not None
                            else None
                        )
                        alert["live"] = round(track.live, 3)
                    alerts.append(alert)
            base = f"monitor.slo.{name}"
            counters.set_counter(
                f"{base}.burning", float(_SLO_STATE_LEVEL[track.state])
            )
            counters.set_counter(f"{base}.fast_burn", round(track.fast_burn, 4))
            counters.set_counter(f"{base}.slow_burn", round(track.slow_burn, 4))
            counters.set_counter(f"{base}.value", round(value, 4))
        return alerts

    def report(self) -> dict:
        """`ctrl.monitor.slo` / `breeze monitor slo` payload."""
        return {
            "node": self.node_name,
            "ts_ms": int(time.time() * 1000),
            "fast_window_s": self.cfg.slo_fast_window_s,
            "slow_window_s": self.cfg.slo_slow_window_s,
            "burn_threshold": self.cfg.slo_burn_threshold,
            "slos": {
                name: {
                    "state": t.state,
                    "kind": t.spec.get("kind", "stat"),
                    "source": t.spec["source"],
                    "threshold": float(t.spec["threshold"]),
                    "value": round(t.value, 3),
                    "fast_burn": round(t.fast_burn, 3),
                    "slow_burn": round(t.slow_burn, 3),
                    "alerts": t.alerts,
                    "last_transition_ms": t.last_transition_ms,
                    **(
                        {
                            "baseline": round(t.baseline, 3),
                            "live": round(t.live, 3),
                        }
                        if t.baseline is not None
                        else {}
                    ),
                }
                for name, t in self._tracks.items()
            },
        }


class FlightRecorder:
    """Always-on bounded black box; freezes to a post-mortem bundle.

    Pull-based by design: NOTHING hooks the hot path. The monitor tick
    appends one raw-counter dict copy to a bounded ring (microseconds),
    interesting LogSamples get noted into a bounded event deque, and
    the expensive gathering — closed trace roots, windowed statistics,
    the kernel ledger, the Chrome export — happens only at trigger
    time. That's what keeps untriggered overhead inside the ≤1% bench
    budget.

    A trigger freezes everything into a self-contained directory
    bundle: `bundle.json` (trigger attribution + ring + traces +
    counters + ledger) and `trace.json` (Chrome trace-event export,
    loadable in ui.perfetto.dev). Automatic triggers are rate-limited
    by flight_recorder_min_interval_s; manual dumps bypass the limit.
    """

    def __init__(self, node_name: str, cfg: MonitorConfig):
        self.node_name = node_name
        self.cfg = cfg
        self.dir = cfg.flight_recorder_dir or os.path.join(
            tempfile.gettempdir(), "openr_tpu_flightrec"
        )
        self._ring = max(1, int(cfg.flight_recorder_ring))
        self._counter_ring: collections.deque = collections.deque(
            maxlen=self._ring
        )
        self._events: collections.deque = collections.deque(
            maxlen=max(self._ring * 4, 128)
        )
        self._last_trigger = -float("inf")
        self.bundles: collections.deque = collections.deque(maxlen=8)

    def record_tick(self) -> None:
        """Cheap periodic sample: raw counters only (one dict copy
        under the registry lock) — no stat-window aggregation here."""
        self._counter_ring.append(
            {
                "ts_ms": int(time.time() * 1000),
                "counters": counters.raw_counters(),
            }
        )

    def note_event(self, event: str, values: Optional[dict] = None) -> None:
        """Record a notable event (sentinel/supervisor/slo/divergence
        LogSamples) into the ring so the bundle shows the lead-up."""
        self._events.append(
            {
                "ts_ms": int(time.time() * 1000),
                "event": event,
                **(values or {}),
            }
        )

    def trigger(
        self,
        reason: str,
        detail: Optional[dict] = None,
        extra: Optional[dict] = None,
        force: bool = False,
    ) -> Optional[dict]:
        """Freeze the ring and write a bundle. Returns the bundle record
        or None (rate-limited / write failed). Runs synchronously —
        async callers push it onto a worker thread."""
        now = time.monotonic()
        if (
            not force
            and now - self._last_trigger
            < self.cfg.flight_recorder_min_interval_s
        ):
            counters.increment("monitor.flight_recorder.suppressed")
            return None
        self._last_trigger = now
        bundle = self._freeze(reason, detail, extra)
        try:
            path = self._write(bundle)
        except OSError:
            counters.increment("monitor.flight_recorder.write_errors")
            log.warning("flight recorder: bundle write failed", exc_info=True)
            return None
        counters.increment("monitor.flight_recorder.triggers")
        record = {
            "path": path,
            "reason": reason,
            "ts_ms": bundle["trigger"]["ts_ms"],
        }
        self.bundles.append(record)
        log.warning("flight recorder: bundle %s → %s", reason, path)
        return record

    def _freeze(
        self, reason: str, detail: Optional[dict], extra: Optional[dict]
    ) -> dict:
        # deferred: ops pulls in the device toolchain; the recorder must
        # construct in processes that never touch a solver
        from openr_tpu.ops.xla_cache import ledger

        counters_snap, stats = counters.export_snapshot()
        bundle = {
            "schema": "openr-tpu-flight-recorder/1",
            "node": self.node_name,
            "trigger": {
                "reason": reason,
                "ts_ms": int(time.time() * 1000),
                "detail": detail or {},
            },
            "traces": tracer.get_traces(limit=self._ring),
            "counters": counters_snap,
            "statistics": stats,
            "kernel_ledger": ledger.snapshot(),
            "events": list(self._events),
            "counter_history": list(self._counter_ring),
        }
        if extra:
            bundle.update(extra)
        return bundle

    def _write(self, bundle: dict) -> str:
        reason = "".join(
            c if c.isalnum() or c in "-_" else "-"
            for c in bundle["trigger"]["reason"]
        )
        path = os.path.join(
            self.dir, f"{self.node_name}-{bundle['trigger']['ts_ms']}-{reason}"
        )
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "bundle.json"), "w") as f:
            json.dump(bundle, f, indent=1, sort_keys=True, default=str)
        with open(os.path.join(path, "trace.json"), "w") as f:
            f.write(tracer.export_chrome_json(limit=self._ring))
        self._prune()
        return path

    def _disk_bundles(self) -> list[dict]:
        """This node's bundle directories on disk, newest first. Only
        OUR prefix: several in-process nodes may share the directory."""
        prefix = f"{self.node_name}-"
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            if not name.startswith(prefix):
                continue
            path = os.path.join(self.dir, name)
            if not os.path.isdir(path):
                continue
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                continue
            rest = name[len(prefix):]
            ts_ms, _, reason = rest.partition("-")
            out.append({
                "path": path,
                "reason": reason,
                "ts_ms": int(ts_ms) if ts_ms.isdigit() else 0,
                "mtime": mtime,
                "replayable": os.path.exists(
                    os.path.join(path, "bundle.json")
                ),
            })
        out.sort(key=lambda b: (b["mtime"], b["path"]), reverse=True)
        return out

    def _prune(self) -> None:
        """On-disk retention: keep the newest flight_recorder_keep of
        this node's bundle directories (0 = unbounded, the pre-retention
        behavior). The in-memory deque was always capped; the disk was
        not — a flapping trigger must not fill the partition."""
        keep = int(getattr(self.cfg, "flight_recorder_keep", 0))
        if keep <= 0:
            return
        for stale in self._disk_bundles()[keep:]:
            try:
                shutil.rmtree(stale["path"])
            except OSError:
                counters.increment("monitor.flight_recorder.write_errors")
                log.warning(
                    "flight recorder: prune failed for %s",
                    stale["path"], exc_info=True,
                )
                continue
            counters.increment("monitor.flight_recorder.pruned")

    def list_bundles(self) -> dict:
        """`breeze monitor bundles` payload: what is on disk (post
        retention) and what the in-memory record ring remembers."""
        disk = self._disk_bundles()
        for b in disk:
            b.pop("mtime", None)
        return {
            "dir": self.dir,
            "keep": int(getattr(self.cfg, "flight_recorder_keep", 0)),
            "disk": disk,
            "memory": list(self.bundles),
        }


class Monitor(Actor):
    """ref MonitorBase.h:32."""

    def __init__(
        self,
        node_name: str,
        config: MonitorConfig,
        log_sample_queue: RQueue,
        interval_s: float = 1.0,
    ):
        super().__init__(f"monitor:{node_name}")
        self.node_name = node_name
        self.cfg = config
        self._log_samples = log_sample_queue
        self._interval_s = interval_s
        self.event_logs: collections.deque[LogSample] = collections.deque(
            maxlen=config.max_event_log_entries
        )
        self._process_start = time.monotonic()
        # fleet-health sources, wired post-construction (the kvstore and
        # watchdog outlive/predate the monitor differently per harness)
        self._kvstore = None
        self._watchdog = None
        # seed from wall clock so a restarted node's first advertisement
        # beats the TTL'd remnant of its previous incarnation
        self._health_version = int(time.time())
        # OpenMetrics scrape server (runtime/metrics_export.py), started
        # in on_start when cfg.metrics_port is set
        self.metrics_exporter = None
        # the monitor owns the observability config, so the tracing
        # kill-switch rides on it (ISSUE: disabled tracing must cost no
        # more than a dict lookup per queue push)
        tracer.configure(enabled=config.enable_tracing)
        self.slo_engine = (
            SloEngine(node_name, config) if config.slos else None
        )
        self.flight_recorder = (
            FlightRecorder(node_name, config)
            if config.enable_flight_recorder
            else None
        )
        # persistent perf-baseline ledger (runtime/perf_ledger.py): the
        # baseline_drift SLO kind reads it, the recording loop below
        # appends to it. "" keeps this process disk-free.
        self.perf_ledger = configure_perf_ledger(config.perf_ledger_dir)
        self._last_perf_record = time.monotonic()
        # divergence-events watermark for the edge-triggered recorder
        # trigger (distinct from the SLO, which has its own baseline)
        self._prev_divergence_events = float(
            counters.get_counter("kvstore.divergence.events") or 0.0
        )

    def attach_fleet_sources(self, kvstore=None, watchdog=None) -> None:
        """Wire the health summary's inputs: the KvStore actor to
        advertise `monitor:health:<node>` through, and the watchdog
        whose fired-state the summary reports. Either may stay None —
        the health loop skips advertisement without a kvstore."""
        if kvstore is not None:
            self._kvstore = kvstore
        if watchdog is not None:
            self._watchdog = watchdog

    async def on_start(self) -> None:
        self.add_task(self._log_loop(), name=f"{self.name}.logs")
        self.add_task(self._metrics_loop(), name=f"{self.name}.metrics")
        if self.cfg.enable_fleet_health:
            self.add_task(self._health_loop(), name=f"{self.name}.health")
        if self.cfg.metrics_port is not None:
            # OpenMetrics exposition on the monitor's own event base —
            # a scrape renders the registry inline, no background work
            from openr_tpu.runtime.metrics_export import MetricsExporter

            self.metrics_exporter = MetricsExporter(
                listen_addr=self.cfg.metrics_listen_addr,
                port=self.cfg.metrics_port,
            )
            await self.metrics_exporter.start()
            log.info(
                "monitor %s: /metrics on %s:%d",
                self.node_name,
                self.cfg.metrics_listen_addr,
                self.metrics_exporter.port,
            )

    async def on_stop(self) -> None:
        if self.metrics_exporter is not None:
            await self.metrics_exporter.stop()
            self.metrics_exporter = None

    async def _log_loop(self) -> None:
        while True:
            sample = await self._log_samples.get()
            if isinstance(sample, LogSample):
                if (
                    self.event_logs.maxlen is not None
                    and len(self.event_logs) >= self.event_logs.maxlen
                ):
                    # the bounded deque evicts the oldest silently —
                    # make the loss visible (satellite: dropped samples
                    # looked like they never happened)
                    counters.increment("monitor.event_logs.dropped")
                self.event_logs.append(sample)
                counters.increment("monitor.event_logs")
                await self._observe_sample(sample)

    # LogSample events that trip the flight recorder, keyed to the
    # trigger-attribution reason the bundle carries
    _TRIGGER_EVENTS = {
        "DECISION_SENTINEL_ANOMALY": "sentinel_anomaly",
        "SUPERVISOR_RESTART": "supervisor_restart",
        "DECISION_SOLVER_DEGRADED": "solver_failover",
        # retrace-after-warmup (ops/xla_cache.retrace): a silent
        # recompile on a supposedly-warm kernel is a routing-stale
        # stall in the making — freeze the evidence
        "DEVICE_RETRACE": "device_retrace",
        # every overload-ladder transition (runtime/overload.py) freezes
        # a bundle: the state the system was in when it downshifted IS
        # the incident evidence
        "OVERLOAD_STATE_CHANGE": "overload",
    }
    # LogSample categories worth keeping in the recorder's event ring
    # even when they don't trigger (the bundle shows the lead-up)
    _NOTE_CATEGORIES = {"sentinel", "supervisor", "slo", "spark", "overload"}

    async def _observe_sample(self, sample: LogSample) -> None:
        recorder = self.flight_recorder
        if recorder is None:
            return
        if sample.values.get("category") in self._NOTE_CATEGORIES:
            recorder.note_event(
                sample.event, {"node": sample.node_name, **sample.values}
            )
        reason = self._TRIGGER_EVENTS.get(sample.event)
        if reason is not None:
            await self._trigger_recorder(
                reason,
                {
                    "event": sample.event,
                    "node": sample.node_name,
                    **sample.values,
                },
            )

    async def _trigger_recorder(
        self,
        reason: str,
        detail: dict,
        force: bool = False,
        extra: Optional[dict] = None,
    ) -> Optional[dict]:
        recorder = self.flight_recorder
        if recorder is None:
            return None
        merged = dict(extra or {})
        if self.slo_engine is not None:
            merged["slo"] = self.slo_engine.report()
        # latency-budget annex: which component owned the recent epochs'
        # wall time (and whether conservation held) at trigger time —
        # SLO-burn and perf-regression triage starts from the waterfall
        from openr_tpu.runtime.latency_budget import latency_budget

        budget = latency_budget.snapshot()
        if budget.get("epochs"):
            merged["budget"] = budget
        # inputs annex: the black-box recorder's LSDB snapshot + event
        # ring + per-epoch digest ledger — what makes this bundle
        # replayable offline (tools/replay.py). Built here on the loop
        # (the recorder is loop-owned Decision state), cheap copy.
        from openr_tpu.runtime.replay_log import get_recorder

        replay_rec = get_recorder(self.node_name)
        if replay_rec is not None:
            inputs = replay_rec.export()
            if inputs is not None:
                merged["inputs"] = inputs
        # the freeze walks lock-protected registries and the write hits
        # disk — worker thread, never the control-plane event loop
        return await asyncio.to_thread(
            recorder.trigger, reason, detail, merged or None, force
        )

    async def _observability_tick(self) -> None:
        """SLO evaluation + divergence edge detection + recorder tick —
        one call per metrics interval."""
        recorder = self.flight_recorder
        div = float(
            counters.get_counter("kvstore.divergence.events") or 0.0
        )
        if div > self._prev_divergence_events:
            if recorder is not None:
                recorder.note_event("LSDB_DIVERGENCE", {"events": div})
            await self._trigger_recorder(
                "divergence",
                {
                    "divergence_events": div,
                    "previous": self._prev_divergence_events,
                },
            )
        self._prev_divergence_events = div
        if self.slo_engine is not None:
            for alert in self.slo_engine.evaluate():
                sample = LogSample(
                    event="SLO_BURN_ALERT",
                    node_name=self.node_name,
                    values={"category": "slo", **alert},
                )
                self.event_logs.append(sample)
                counters.increment("monitor.event_logs")
                log.warning("SLO burn alert: %s", sample.to_json())
                if recorder is not None:
                    recorder.note_event(
                        sample.event,
                        {"node": sample.node_name, **sample.values},
                    )
                if alert.get("kind") == "baseline_drift":
                    # a drifting kernel is a perf regression, not an
                    # availability burn: the bundle gets the ledger
                    # delta so triage starts from baseline-vs-live
                    await self._trigger_recorder(
                        "perf_regression",
                        alert,
                        extra={
                            "perf_ledger_delta": {
                                "slo": alert["slo"],
                                "baseline": alert.get("baseline"),
                                "live": alert.get("live"),
                                "ratio": alert.get("value"),
                                "threshold": alert.get("threshold"),
                                "ledger": self.perf_ledger.snapshot(),
                            }
                        },
                    )
                else:
                    await self._trigger_recorder(
                        f"slo_burn:{alert['slo']}", alert
                    )
        self._feed_overload_controller()
        self._maybe_record_live_perf()
        if recorder is not None:
            recorder.record_tick()

    def _feed_overload_controller(self) -> None:
        """Feed the node's overload controller (runtime/overload.py) the
        signals only the Monitor sees: host RSS, worst-device HBM
        pressure, and whether any SLO track is burning. Decision feeds
        queue depth from its own fiber; both run on this loop, so the
        controller needs no locking."""
        ctl = get_overload_controller(self.node_name)
        if ctl is None:
            return
        burning = self.slo_engine is not None and any(
            t.state != "ok" for t in self.slo_engine._tracks.values()
        )
        ctl.observe(
            hbm_frac=device_stats.hbm_pressure(allow_import=False),
            rss_mb=current_rss_mb(),
            slo_burning=burning,
        )

    def _maybe_record_live_perf(self) -> None:
        """Append a live solve observation (kernel "solve", signature/
        variant "live") every perf_ledger_record_interval_s so a
        long-running daemon accretes its own baseline. Skipped while any
        drift SLO is burning — recording through a regression would pull
        the baseline toward the regressed latency and mask it."""
        lg = self.perf_ledger
        if not lg.enabled:
            return
        now = time.monotonic()
        interval = self.cfg.perf_ledger_record_interval_s
        if now - self._last_perf_record < interval:
            return
        if self.slo_engine is not None and any(
            t.spec.get("kind") == "baseline_drift" and t.state != "ok"
            for t in self.slo_engine._tracks.values()
        ):
            return
        win = (max(interval, 1.0),)
        def agg(stat: str) -> dict:
            return next(
                iter(counters.get_statistics(stat, windows=win).get(stat, {}).values()),
                {},
            )
        spf = agg("decision.spf_ms")
        if not spf.get("count"):
            return  # no solves this window — nothing worth a baseline
        self._last_perf_record = now
        obs = {
            "device_ms": spf.get("p50", 0.0),
            "solves": spf.get("count", 0),
        }
        mat = agg("decision.mat_ms")
        if mat.get("count"):
            obs["mat_ms"] = mat.get("p50", 0.0)
        hbm, _ = device_stats.peak_hbm_mb(allow_import=False)
        if hbm:
            obs["peak_hbm_mb"] = float(hbm)
        # per-component budget baselines: the perf ledger (and therefore
        # tools/perf_diff.py and the CI gate) diffs the BREAKDOWN — a
        # regression report names the component that moved, not just the
        # headline
        from openr_tpu.runtime.latency_budget import BUDGET_COMPONENTS

        for comp in BUDGET_COMPONENTS:
            bagg = agg(f"budget.{comp}_ms")
            if bagg.get("count"):
                obs[f"budget_{comp}_ms"] = bagg.get("p50", 0.0)
        be2e = agg("budget.e2e_ms")
        if be2e.get("count"):
            obs["budget_e2e_ms"] = be2e.get("p50", 0.0)
        bun = agg("budget.unattributed_ms")
        if bun.get("count"):
            obs["budget_unattributed_ms"] = bun.get("p50", 0.0)
        lg.record("solve", obs, signature="live", variant="live")

    async def _metrics_loop(self) -> None:
        """Process gauges (role of SystemMetrics.{h,cpp})."""
        while True:
            usage = resource.getrusage(resource.RUSAGE_SELF)
            counters.set_counter("process.memory.rss_mb", current_rss_mb())
            counters.set_counter("process.memory.max_rss_mb", rss_mb())
            counters.set_counter(
                "process.cpu.total_s", usage.ru_utime + usage.ru_stime
            )
            counters.set_counter(
                "process.uptime_s", time.monotonic() - self._process_start
            )
            if self.cfg.enable_device_telemetry:
                try:
                    # passive poll: only reads jax if something else
                    # already imported it (device_stats._jax)
                    device_stats.export_device_gauges()
                except Exception:
                    counters.increment("monitor.device_poll_errors")
                    log.debug("device gauge export failed", exc_info=True)
            try:
                await self._observability_tick()
            except Exception:
                counters.increment("monitor.slo.tick_errors")
                log.debug("observability tick failed", exc_info=True)
            await asyncio.sleep(self._interval_s)

    # -- fleet health (advertised over the flooding fabric) ----------------

    def health_summary(self) -> dict:
        """One node's health card: the fields an operator triages a
        fleet by. Everything reads from the counter fabric or attached
        sources — cheap enough for every health interval."""
        wd = self._watchdog
        depths = counters.get_counters("messaging.queue.")
        worst_q, worst_depth = "", 0
        for k, v in depths.items():
            if k.endswith(".max_depth") and v >= worst_depth:
                worst_q, worst_depth = k[len("messaging.queue."):-len(".max_depth")], int(v)
        conv = counters.get_statistics(
            "convergence_ms", windows=(600.0,)
        ).get("convergence_ms", {}).get("600", {})
        dev = device_stats.collect_device_stats()
        hbm = [
            e["hbm_in_use_mb"]
            for e in dev["devices"]
            if "hbm_in_use_mb" in e
        ]
        return {
            "node": self.node_name,
            "ts_ms": int(time.time() * 1000),
            "uptime_s": round(time.monotonic() - self._process_start, 1),
            "rss_mb": round(current_rss_mb(), 1),
            "watchdog_fired": wd.fired if wd is not None else None,
            "worst_queue": worst_q,
            "worst_queue_depth": worst_depth,
            "convergence_p99_ms": round(conv.get("p99", 0.0), 3),
            "backend": dev["backend"],
            "hbm_in_use_mb": round(max(hbm), 3) if hbm else None,
            "sentinel_anomalies": int(
                counters.get_counter("decision.sentinel.anomalies") or 0
            ),
            "solver_degraded": bool(
                counters.get_counter("decision.solver.degraded") or 0
            ),
            "supervisor_restarts": int(
                counters.get_counter("runtime.supervisor.restarts") or 0
            ),
            "event_logs_dropped": int(
                counters.get_counter("monitor.event_logs.dropped") or 0
            ),
            # what-if planning activity (PR 6): errors > 0 means an
            # operator's planning query failed — never degraded mode,
            # but worth triage
            "whatif_runs": int(
                (counters.get_counter("whatif.sweeps") or 0)
                + (counters.get_counter("whatif.drains") or 0)
                + (counters.get_counter("whatif.optimizes") or 0)
            ),
            "whatif_errors": int(counters.get_counter("whatif.errors") or 0),
            # incremental-solver engagement (PR 7): a fleet where
            # full_fallbacks tracks solves 1:1 is paying cold-solve
            # latency on every churn event
            "incr_solves": int(
                counters.get_counter("decision.solver.incr.solves") or 0
            ),
            "incr_full_fallbacks": int(
                counters.get_counter("decision.solver.incr.full_fallbacks") or 0
            ),
            # namespaced executable-cache churn: evictions in the incr /
            # whatif LRU budgets mean shape churn is recompiling kernels
            "xla_evictions": int(
                (counters.get_counter("xla_cache.incr_executable_evictions") or 0)
                + (
                    counters.get_counter("xla_cache.whatif_executable_evictions")
                    or 0
                )
            ),
            # LSDB divergence beacons (kvstore digest fabric): true while
            # any peer's advertised digest disagrees with ours
            "lsdb_diverged": bool(
                counters.get_counter("kvstore.divergence.detected") or 0
            ),
            # overload ladder state (runtime/overload.py) — "ok" when no
            # controller is registered, so fleet triage sorts the browned
            # -out nodes to the top without a per-node feature probe
            "overload_state": (
                ctl.state
                if (ctl := get_overload_controller(self.node_name))
                is not None
                else "ok"
            ),
        }

    async def _health_loop(self) -> None:
        """Advertise this node's health card into KvStore as a TTL'd
        `monitor:health:<node>` key — the network observes itself over
        its own flooding fabric; `breeze monitor fleet` on ANY node
        renders every node's card. TTL ~3 intervals: a dead node's card
        expires instead of lying forever."""
        interval_s = max(self._interval_s, 1.0)
        while True:
            await asyncio.sleep(interval_s)
            if self._kvstore is None:
                continue
            try:
                await self._advertise_health(interval_s)
            except Exception:
                counters.increment("monitor.health_advert_errors")
                log.debug("fleet health advertisement failed", exc_info=True)

    async def _advertise_health(self, interval_s: float) -> None:
        from openr_tpu.types import Value

        payload = json.dumps(self.health_summary(), sort_keys=True).encode()
        self._health_version += 1
        ttl_ms = max(int(interval_s * 3000), 2500)
        key = f"monitor:health:{self.node_name}"
        for area in list(getattr(self._kvstore, "areas", None) or ["0"]):
            await self._kvstore.set_key_vals(
                area,
                {
                    key: Value(
                        version=self._health_version,
                        originator_id=self.node_name,
                        value=payload,
                        ttl_ms=ttl_ms,
                    )
                },
            )
        counters.increment("monitor.health.advertisements")

    # -- API (ref getEventLogs) --------------------------------------------

    async def get_event_logs(
        self, category: Optional[str] = None
    ) -> list[str]:
        """Retained LogSamples, optionally filtered: `category` matches
        the event name exactly, as a dotted prefix ("spark" matches
        "spark.neighbor_up"), or the sample's values["category"]."""
        samples = list(self.event_logs)
        if category:
            samples = [
                s
                for s in samples
                if s.event == category
                or s.event.startswith(category + ".")
                or s.values.get("category") == category
            ]
        return [s.to_json() for s in samples]

    def slo_report(self) -> dict:
        """ctrl.monitor.slo payload; enabled=False when no SLO table."""
        if self.slo_engine is None:
            return {
                "node": self.node_name,
                "enabled": False,
                "slos": {},
            }
        return {"enabled": True, **self.slo_engine.report()}

    async def dump_flight_recorder(
        self, reason: str = "manual", detail: Optional[dict] = None
    ) -> dict:
        """ctrl.monitor.dump — operator-requested bundle; bypasses the
        automatic-trigger rate limit."""
        if self.flight_recorder is None:
            return {"ok": False, "error": "flight recorder disabled"}
        record = await self._trigger_recorder(
            reason, detail or {}, force=True
        )
        if record is None:
            return {"ok": False, "error": "bundle write failed"}
        return {"ok": True, **record}

    async def flight_recorder_bundles(self) -> dict:
        """ctrl.monitor.bundles — on-disk + in-memory bundle listing."""
        if self.flight_recorder is None:
            return {"ok": False, "error": "flight recorder disabled"}
        return {"ok": True, **self.flight_recorder.list_bundles()}

    async def record_replay_bundle(self, reason: str = "record") -> dict:
        """ctrl.monitor.record — operator-requested REPLAYABLE bundle:
        asks the input recorder to re-anchor its LSDB snapshot at the
        next solve (tightening future bundles' replay window), then
        freezes a bundle carrying the current `inputs` annex."""
        from openr_tpu.runtime.replay_log import get_recorder

        rec = get_recorder(self.node_name)
        if rec is not None:
            rec.request_snapshot()
        out = await self.dump_flight_recorder(reason=reason)
        out["replayable"] = rec is not None and rec.export() is not None
        return out

# -- heap profiling (role of MonitorBase::dumpHeapProfile,
# MonitorBase.h:54 — the reference hooks jemalloc; the Python runtime's
# native profiler is tracemalloc). Process-global, so plain functions:
# the ctrl server serves them with or without a Monitor actor wired. ----


def start_heap_profile(frames: int = 1) -> dict:
    """frames > 1 multiplies tracemalloc's per-allocation overhead; the
    dump groups by the allocation site (top frame), so 1 is the useful
    default — pass more only when chasing a shared helper's callers."""
    import tracemalloc

    if tracemalloc.is_tracing():
        return {"ok": True, "already_tracing": True}
    tracemalloc.start(max(1, frames))
    return {"ok": True, "already_tracing": False}


async def dump_heap_profile(top: int = 25, stop: bool = False) -> dict:
    """Top allocation sites since start_heap_profile; optionally stops
    tracing. Snapshot + grouping walk every live trace (seconds on a
    busy daemon), so they run on a worker thread — the control-plane
    event loop (Spark hellos, KvStore timers) keeps running."""
    import asyncio
    import tracemalloc

    if not tracemalloc.is_tracing():
        return {"ok": False, "error": "not tracing — start first"}

    def _collect():
        snap = tracemalloc.take_snapshot()
        current, peak = tracemalloc.get_traced_memory()
        return snap.statistics("lineno")[: max(1, top)], current, peak

    stats, current, peak = await asyncio.to_thread(_collect)
    if stop:
        tracemalloc.stop()
    return {
        "ok": True,
        "traced_current_kb": round(current / 1024, 1),
        "traced_peak_kb": round(peak / 1024, 1),
        "top": [
            {
                "site": str(s.traceback[0]) if s.traceback else "?",
                "size_kb": round(s.size / 1024, 1),
                "count": s.count,
            }
            for s in stats
        ],
    }


def _default_crash_handler(reason: str) -> None:
    """ref Watchdog::fireCrash — kill the process so the supervisor
    (systemd) restarts it with fresh state."""
    log.critical("watchdog: %s — aborting process", reason)
    sys.stderr.flush()
    os._exit(70)  # EX_SOFTWARE


class Watchdog(Actor):
    """ref Watchdog.h:20."""

    def __init__(
        self,
        node_name: str,
        config: WatchdogConfig,
        crash_handler: Optional[Callable[[str], None]] = None,
    ):
        super().__init__(f"watchdog:{node_name}")
        self.cfg = config
        self._watched_actors: list[Actor] = []
        self._watched_queues: list[ReplicateQueue] = []
        self._crash = crash_handler or _default_crash_handler
        self.fired: Optional[str] = None  # reason, for tests
        # reader names seen last sweep, per queue: the delta vs the
        # current sweep is the prune set (ghost-gauge cleanup)
        self._prev_readers: dict[str, set[str]] = {}

    def watch_actor(self, actor: Actor) -> None:
        """ref addEvb — actors stamp last_alive_ts (actor.py heartbeat).

        Also wires the actor's fiber supervisor to this watchdog: config
        knobs override the actor defaults, and a fiber that exhausts its
        crash budget escalates into _fire (same path as a stalled
        heartbeat). The supervisor reads the knobs lazily, so applying
        them after the actor started is fine."""
        self._watched_actors.append(actor)
        actor.crash_budget = self.cfg.supervisor_crash_budget
        actor.restart_backoff_initial_s = self.cfg.supervisor_backoff_initial_s
        actor.restart_backoff_max_s = self.cfg.supervisor_backoff_max_s
        actor._escalate = self._fire

    def watch_queue(self, queue: ReplicateQueue) -> None:
        """ref addQueue — depth counters (Watchdog.h:45-48)."""
        self._watched_queues.append(queue)

    async def on_start(self) -> None:
        self.add_task(self._watch_loop(), name=f"{self.name}.watch")

    async def _watch_loop(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.interval_s)
            self._check_threads()
            self._check_memory()
            self._export_queue_stats()

    def _check_threads(self) -> None:
        """ref monitorThreadStatus."""
        for actor in self._watched_actors:
            stale_s = actor.seconds_since_alive()
            if stale_s > self.cfg.thread_timeout_s:
                self._fire(
                    f"actor {actor.name} stalled for {stale_s:.1f}s "
                    f"(> {self.cfg.thread_timeout_s}s)"
                )
                return

    def _check_memory(self) -> None:
        """ref monitorMemory."""
        rss = rss_mb()
        counters.set_counter("watchdog.rss_mb", rss)
        if rss > self.cfg.max_memory_mb:
            self._fire(
                f"memory {rss:.0f}MB exceeds ceiling "
                f"{self.cfg.max_memory_mb}MB"
            )

    def _export_queue_stats(self) -> None:
        for q in self._watched_queues:
            stats = q.stats()
            base = f"messaging.queue.{stats['name']}"
            counters.set_counter(f"{base}.max_depth", stats["max_depth"])
            counters.set_counter(f"{base}.writes", stats["writes"])
            # per-reader depth/reads + replica count: a wedged reader
            # (depth growing, reads flat) is visible here long before
            # the thread-timeout crash fires
            counters.set_counter(
                f"{base}.replicas", len(stats["readers"])
            )
            current = set()
            for r in stats["readers"]:
                current.add(r["name"])
                counters.set_counter(
                    f"{base}.reader.{r['name']}.depth", r["depth"]
                )
                counters.set_counter(
                    f"{base}.reader.{r['name']}.reads", r["reads"]
                )
            # prune gauges for readers that disappeared since the last
            # sweep: churny readers (ctrl subscriptions, long-polls)
            # would otherwise leave ghost gauges forever and grow
            # counter cardinality without bound. Trailing dot so reader
            # "r" never swallows reader "r2".
            for gone in self._prev_readers.get(stats["name"], set()) - current:
                counters.erase_prefix(f"{base}.reader.{gone}.")
            self._prev_readers[stats["name"]] = current

    def _fire(self, reason: str) -> None:
        if self.fired is None:
            self.fired = reason
            counters.increment("watchdog.crashes_fired")
            self._crash(reason)
