"""Replicated message queues — the inter-module fabric.

Role of the reference's openr/messaging/Queue.h (RQueue:43, RWQueue:83) and
ReplicateQueue.h:34: MPMC fan-out where every reader sees every write,
blocking reads suspend the caller (folly fibers there, asyncio tasks here),
and close() unblocks all pending reads with QueueClosedError.

Unlike the reference we are single-event-loop asyncio rather than
one-thread-per-module, so the queue is a plain deque + condition per reader;
the actor model (runtime/actor.py) preserves the single-writer discipline.
"""

from __future__ import annotations

import asyncio
import collections
from typing import Generic, TypeVar

T = TypeVar("T")

# bound on first traced push (runtime.__init__ -> actor -> messaging
# makes a top-level import circular); None until tracing is ever used
_tracer = None

# fault hook, lazily bound for the same circular-import reason; the
# armed-site check itself is one dict lookup (runtime/faults.py)
_maybe_fail = None


class QueueClosedError(RuntimeError):
    """Raised from get() once the queue is closed and drained
    (ref messaging/Queue.h QUEUE_CLOSED)."""


class RQueue(Generic[T]):
    """Read endpoint. Each reader has a private buffer; every push to the
    parent ReplicateQueue lands in every reader's buffer."""

    def __init__(self, name: str = ""):
        self.name = name
        self._buf: collections.deque[T] = collections.deque()
        self._event = asyncio.Event()
        self._closed = False
        self._reads = 0

    def _push(self, item: T) -> None:
        self._buf.append(item)
        self._event.set()

    def _close(self) -> None:
        self._closed = True
        self._event.set()

    def size(self) -> int:
        return len(self._buf)

    async def get(self) -> T:
        while True:
            if self._buf:
                self._reads += 1
                item = self._buf.popleft()
                if not self._buf and not self._closed:
                    self._event.clear()
                return item
            if self._closed:
                raise QueueClosedError(self.name)
            await self._event.wait()

    def try_get(self) -> tuple[bool, T | None]:
        """Non-blocking read: (ok, item)."""
        if self._buf:
            self._reads += 1
            return True, self._buf.popleft()
        if self._closed:
            raise QueueClosedError(self.name)
        return False, None


class ReplicateQueue(Generic[T]):
    """Write endpoint; fan-out to all readers
    (ref messaging/ReplicateQueue.h:34)."""

    def __init__(self, name: str = ""):
        self.name = name
        self._readers: list[RQueue[T]] = []
        self._closed = False
        self._writes = 0

    def get_reader(self, name: str = "") -> RQueue[T]:
        if self._closed:
            raise QueueClosedError(self.name)
        r = RQueue(name or f"{self.name}#{len(self._readers)}")
        self._readers.append(r)
        return r

    def push(self, item: T, trace=None) -> int:
        """Replicate to every reader; returns replication count.

        `trace` (a runtime.tracing.TraceContext) rides along in the
        tracer's side-table so consumers can pick it up with
        tracing.context_of(item); when tracing is off producers pass
        None and this costs one comparison."""
        if self._closed:
            raise QueueClosedError(self.name)
        global _maybe_fail
        if _maybe_fail is None:
            from openr_tpu.runtime.faults import maybe_fail as _mf
            _maybe_fail = _mf
        _maybe_fail("queue.push")
        if trace is not None:
            global _tracer
            if _tracer is None:
                from openr_tpu.runtime.tracing import tracer as _t
                _tracer = _t
            _tracer.attach(item, trace)
        self._writes += 1
        for r in self._readers:
            r._push(item)
        return len(self._readers)

    def remove_reader(self, reader: RQueue[T]) -> None:
        """Unregister a reader (closes it): transient consumers — e.g.
        per-subscription ctrl streams — must not accumulate unread buffers
        for the queue's lifetime."""
        try:
            self._readers.remove(reader)
        except ValueError:
            return
        reader._close()

    def close(self) -> None:
        self._closed = True
        for r in self._readers:
            r._close()

    @property
    def num_readers(self) -> int:
        return len(self._readers)

    @property
    def num_writes(self) -> int:
        return self._writes

    def stats(self) -> dict:
        """Queue-depth stats for the watchdog (ref Watchdog.h:45-48)."""
        return {
            "name": self.name,
            "writes": self._writes,
            "readers": [
                {"name": r.name, "depth": r.size(), "reads": r._reads}
                for r in self._readers
            ],
            "max_depth": max((r.size() for r in self._readers), default=0),
        }
