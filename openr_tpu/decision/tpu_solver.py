"""TPU route-computation backend — the project's differentiator.

Replaces the reference's per-root memoized Dijkstra + per-prefix scalar
loops (openr/decision/LinkState.cpp:836-911 runSpf + SpfSolver.cpp:460-646
buildRouteDb) with one fused, jit-compiled pipeline over the ops/csr.py
array mirror:

  1. SSSP: frontier-synchronous Bellman-Ford as a fixpoint of
         dist'[v] = min(dist[v], min_k dist[in_nbr[v,k]] + in_w[v,k])
     under lax.while_loop — dense [N_cap, K_cap] gather + min-reduce,
     no scatter, static shapes. Overloaded-node transit drain is the same
     mask the reference applies in its relax step (root exempt).
  2. First-hop ("next hop") extraction: boolean fixpoint over the shortest-
     path DAG seeded at the root's out-edge slots — matches runSpf's ECMP
     `>=` accumulation (dist[u]+w == dist[v] predicate,
     LinkState.cpp:885-901).
  3. Best-route selection: vectorized lexicographic selection over the
     prefix x announcer matrix in the reference's order (path_preference
     desc, source_preference desc, advertised distance asc —
     LsdbUtil.cpp:842), drained-announcer filter with all-drained
     fallback (SpfSolver.cpp:709-731), then min-IGP-metric announcer set
     and the union of their first-hop masks.

The memoize-per-root-on-demand strategy is deliberately replaced by
compute-everything-batched: one TPU launch produces the full RIB's
next-hop structure; roots batch via vmap for whole-fabric computation.

Scope (round 2): single-area LSDBs with IP/SP_ECMP prefixes run on
device; KSP2 / UCMP / SR_MPLS / prepend-label prefixes and multi-area
LSDBs fall back to the CPU oracle (decision/spf_solver.py) per prefix —
behavior is identical by construction and enforced by differential tests
(tests/test_tpu_solver.py). MPLS label routes are host-built (they are
O(adjacent links), not hot).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.rib import DecisionRouteDb, NextHop, RibUnicastEntry
from openr_tpu.decision.spf_solver import SpfSolver, select_best_node_area
from openr_tpu.ops.csr import (
    INF32,
    EllGraph,
    PrefixMatrix,
    build_ell,
    build_prefix_matrix,
)
from openr_tpu.types import (
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
    parse_prefix,
)

INF = int(INF32)
_NEG = -(2**31)


# ---------------------------------------------------------------------------
# jitted kernels (pure functions of arrays; shapes static per capacity class)
# ---------------------------------------------------------------------------

# relaxation steps fused per while_loop iteration: each on-device loop trip
# has fixed dispatch overhead, and a single [N_cap, K_cap] relax is tiny —
# fusing amortizes the trip cost over UNROLL steps (extra steps past the
# fixpoint are no-ops)
_UNROLL = 8


def _sssp_kernel(in_nbr, in_w, in_up, node_over, root):
    """dist[v] fixpoint; int32 [N_cap]."""
    import jax
    import jax.numpy as jnp

    n = in_nbr.shape[0]
    dist0 = jnp.full((n,), INF, jnp.int32).at[root].set(0)
    # a source node may relax its out-edges iff it is the root or not
    # overloaded (transit drain, ref LinkState.cpp:858-866)
    usable = in_up & (in_nbr >= 0) & ((in_nbr == root) | ~node_over[in_nbr])

    def relax(dist):
        nbr_dist = dist[in_nbr]  # [N, K] gather
        cand = jnp.where(
            usable & (nbr_dist < INF), nbr_dist + in_w, INF
        ).min(axis=1)
        return jnp.minimum(dist, cand)

    def body(state):
        dist, _ = state
        new = dist
        for _ in range(_UNROLL):
            new = relax(new)
        return new, jnp.any(new != dist)

    dist, _ = jax.lax.while_loop(lambda s: s[1], body, (dist0, jnp.bool_(True)))
    return dist


def _next_hop_kernel(in_nbr, in_w, in_up, node_over, root, dist, root_nbr, root_w, root_up):
    """First-hop slot masks nh[v, d]: root's out-edge slot d lies on a
    shortest path to v. bool [N_cap, D_cap]."""
    import jax
    import jax.numpy as jnp

    n, _ = in_nbr.shape
    d_cap = root_nbr.shape[0]
    # seed: slot d reaches its neighbor iff that direct edge achieves the
    # neighbor's shortest distance (ref: direct neighbor adds itself)
    slot_ok = (root_nbr >= 0) & root_up & (dist[jnp.clip(root_nbr, 0, n - 1)] == root_w)
    seed = jnp.zeros((n, d_cap), bool).at[
        jnp.where(root_nbr >= 0, root_nbr, n), jnp.arange(d_cap)
    ].set(slot_ok, mode="drop")
    # propagate over shortest-path in-edges from non-root, non-overloaded
    # parents (root's contribution is exactly the seed)
    ok_parent = (
        in_up
        & (in_nbr >= 0)
        & (in_nbr != root)
        & ~node_over[in_nbr]
        & (dist[in_nbr] < INF)
        & (dist[in_nbr] + in_w == dist[:, None])
    )

    def step(nh):
        prop = jnp.any(ok_parent[:, :, None] & nh[in_nbr], axis=1)
        return seed | prop

    def body(state):
        nh, _ = state
        new = nh
        for _ in range(_UNROLL):
            new = step(new)
        return new, jnp.any(new != nh)

    nh, _ = jax.lax.while_loop(lambda s: s[1], body, (seed, jnp.bool_(True)))
    return nh


def _select_metric_kernel(dist, node_over, ann_node, ann_valid, path_pref, source_pref, dist_adv):
    """Vectorized per-prefix best-route selection (no next-hop union):
    returns (igp_metric[P], s3[P,A] post-drain selected set, s4[P,A]
    min-IGP subset, idx clipped announcer indices). Shared by the
    single-chip pipeline and the sharded step so the selection semantics
    (incl. the all-drained fallback, SpfSolver.cpp:709-731) exist once."""
    import jax.numpy as jnp

    n = dist.shape[0]
    idx = jnp.clip(ann_node, 0, n - 1)
    ann_dist = dist[idx]
    reach = ann_valid & (ann_dist < INF)
    pp = jnp.where(reach, path_pref, _NEG)
    s = reach & (pp == pp.max(axis=1, keepdims=True))
    sp = jnp.where(s, source_pref, _NEG)
    s = s & (sp == sp.max(axis=1, keepdims=True))
    da = jnp.where(s, dist_adv, INF)
    s2 = s & (da == da.min(axis=1, keepdims=True))
    # drained-announcer filter; keep unfiltered when all drained
    nd = s2 & ~node_over[idx]
    s3 = jnp.where(nd.any(axis=1, keepdims=True), nd, s2)
    igp = jnp.where(s3, ann_dist, INF)
    metric = igp.min(axis=1)
    s4 = s3 & (igp == metric[:, None])
    return metric, s3, s4, idx


def _select_kernel(dist, nh, node_over, ann_node, ann_valid, path_pref, source_pref, dist_adv):
    """Selection + next-hop union.

    Returns (igp_metric[P], selected[P,A] (post-drain set S3),
    nh_mask[P,D], has_route[P])."""
    import jax.numpy as jnp

    metric, s3, s4, idx = _select_metric_kernel(
        dist, node_over, ann_node, ann_valid, path_pref, source_pref, dist_adv
    )
    nh_mask = jnp.any(s4[:, :, None] & nh[idx], axis=1)
    has_route = s3.any(axis=1) & (metric < INF)
    return metric, s3, nh_mask, has_route


@functools.lru_cache(maxsize=None)
def _jitted_pipeline():
    """Build the fused jit once (lazy so importing this module doesn't pull
    in jax)."""
    import jax

    def pipeline(
        in_nbr, in_w, in_up, node_over,
        root, root_nbr, root_w, root_up,
        ann_node, ann_valid, path_pref, source_pref, dist_adv,
    ):
        dist = _sssp_kernel(in_nbr, in_w, in_up, node_over, root)
        nh = _next_hop_kernel(
            in_nbr, in_w, in_up, node_over, root, dist, root_nbr, root_w, root_up
        )
        metric, s3, nh_mask, has_route = _select_kernel(
            dist, nh, node_over, ann_node, ann_valid, path_pref, source_pref, dist_adv
        )
        return dist, metric, s3, nh_mask, has_route

    return jax.jit(pipeline)


def pack_graph_inputs(
    in_nbr, in_w, in_up, node_over, root_idx, root_nbr, root_w, root_up
) -> np.ndarray:
    """Graph-side device buffer for one vantage point, with every usability
    rule folded into an effective weight on the HOST (the device link is
    bandwidth-bound; fewer arrays = fewer bytes):

      w_eff[v,k] = metric of edge u->v, or INF32 when the slot is padding,
                   the link is down, u is the root (the root cannot be
                   transit for its own routes), or u is overloaded
                   (transit drain, ref LinkState.cpp:858-866)
      root_w[d]  = root's out-slot metric, or INF32 when invalid/down
                   (an overloaded NEIGHBOR keeps its slot: it is a valid
                   destination/first hop, just not transit — its own
                   out-edges are INF via w_eff)

    Layout (int32): in_nbr [N*K] | w_eff [N*K] | root | root_nbr [D] |
    root_w_eff [D].
    """
    src_ok = in_nbr >= 0
    clipped = np.clip(in_nbr, 0, None)
    usable = (
        in_up
        & src_ok
        & (in_nbr != root_idx)
        & ~node_over[clipped]
    )
    w_eff = np.where(usable, in_w, INF32).astype(np.int32)
    rw_eff = np.where((root_nbr >= 0) & root_up, root_w, INF32).astype(np.int32)
    return np.concatenate(
        [
            in_nbr.ravel(),
            w_eff.ravel(),
            np.array([root_idx], np.int32),
            root_nbr,
            rw_eff,
        ]
    ).astype(np.int32, copy=False)


def pack_matrix_inputs(matrix, node_over) -> np.ndarray:
    """Announcer-matrix device buffer; validity and per-announcer drain
    fold into flag bits host-side.

    Layout (int32): ann_node | ann_flags (bit0 valid, bit1 overloaded) |
    path_pref | source_pref | dist_adv, each [P*A]."""
    idx = np.clip(matrix.ann_node, 0, None)
    flags = matrix.ann_valid.astype(np.int32) | (
        node_over[idx].astype(np.int32) << 1
    )
    return np.concatenate(
        [
            matrix.ann_node.ravel(),
            flags.ravel(),
            matrix.path_pref.ravel(),
            matrix.source_pref.ravel(),
            matrix.dist_adv.ravel(),
        ]
    ).astype(np.int32, copy=False)


def _sssp_multi_kernel(in_nbr, w_eff, seeds):
    """Batched SSSP from D seed nodes over host-folded weights:
    dist_d[v] fixpoint, int32 [D, N]. Invalid seeds (-1) yield all-INF."""
    import jax
    import jax.numpy as jnp

    n = in_nbr.shape[0]
    d = seeds.shape[0]
    valid = seeds >= 0
    seed_idx = jnp.clip(seeds, 0, n - 1)
    dist0 = jnp.full((d, n), INF, jnp.int32)
    dist0 = dist0.at[jnp.arange(d), seed_idx].min(
        jnp.where(valid, 0, INF).astype(jnp.int32)
    )
    gather_ok = in_nbr >= 0
    nbr = jnp.clip(in_nbr, 0, n - 1)

    def relax(dist):
        # dist [D, N] -> gather [D, N, K]
        nbr_dist = dist[:, nbr]
        cand = jnp.where(
            gather_ok[None] & (nbr_dist < INF), nbr_dist + w_eff[None], INF
        ).min(axis=2)
        return jnp.minimum(dist, cand)

    def body(state):
        dist, _ = state
        new = dist
        for _ in range(_UNROLL):
            new = relax(new)
        return new, jnp.any(new != dist)

    dist, _ = jax.lax.while_loop(
        lambda s: s[1], body, (dist0, jnp.bool_(True))
    )
    return dist


@functools.lru_cache(maxsize=None)
def _jitted_packed_pipeline(n_cap: int, k_cap: int, d_cap: int, p_cap: int, a_cap: int):
    """Packed-I/O pipeline: graph buffer + matrix buffer in, ONE int8
    buffer out (metric bitcast to bytes).

    Next hops come from a single batched SSSP from the root's D out-slot
    neighbors in G-minus-root: via[d,v] = root_w[d] + dist_d[v], the true
    distance is their min (root pinned to 0), and slot d lies on a
    shortest path to v iff via[d,v] == dist[v] — the same predicate as
    runSpf's ECMP accumulation (LinkState.cpp:885-901) without a second
    fixpoint."""
    import jax
    import jax.numpy as jnp

    nk = n_cap * k_cap
    pa = p_cap * a_cap

    def pipeline(gbuf, mbuf):
        o = 0
        in_nbr = gbuf[o : o + nk].reshape(n_cap, k_cap); o += nk
        w_eff = gbuf[o : o + nk].reshape(n_cap, k_cap); o += nk
        root = gbuf[o]; o += 1
        root_nbr = gbuf[o : o + d_cap]; o += d_cap
        root_w = gbuf[o : o + d_cap]; o += d_cap
        o = 0
        ann_node = mbuf[o : o + pa].reshape(p_cap, a_cap); o += pa
        ann_flags = mbuf[o : o + pa].reshape(p_cap, a_cap); o += pa
        path_pref = mbuf[o : o + pa].reshape(p_cap, a_cap); o += pa
        source_pref = mbuf[o : o + pa].reshape(p_cap, a_cap); o += pa
        dist_adv = mbuf[o : o + pa].reshape(p_cap, a_cap); o += pa
        ann_valid = (ann_flags & 1).astype(bool)
        ann_over = (ann_flags & 2).astype(bool)

        seeds = jnp.where(root_w < INF, root_nbr, -1)
        dist_d = _sssp_multi_kernel(in_nbr, w_eff, seeds)  # [D, N]
        via = jnp.where(
            (root_w[:, None] < INF) & (dist_d < INF),
            root_w[:, None] + dist_d,
            INF,
        )  # [D, N]
        dist = via.min(axis=0).at[root].set(0)  # [N]

        # selection (ref _select_metric_kernel semantics, drain via flags)
        idx = jnp.clip(ann_node, 0, n_cap - 1)
        ann_dist = dist[idx]
        reach = ann_valid & (ann_dist < INF)
        pp = jnp.where(reach, path_pref, _NEG)
        s = reach & (pp == pp.max(axis=1, keepdims=True))
        sp = jnp.where(s, source_pref, _NEG)
        s = s & (sp == sp.max(axis=1, keepdims=True))
        da = jnp.where(s, dist_adv, INF)
        s2 = s & (da == da.min(axis=1, keepdims=True))
        nd = s2 & ~ann_over
        s3 = jnp.where(nd.any(axis=1, keepdims=True), nd, s2)
        igp = jnp.where(s3, ann_dist, INF)
        metric = igp.min(axis=1)
        s4 = s3 & (igp == metric[:, None])

        # per-prefix next-hop slots: union over min-IGP announcers of the
        # slots achieving their shortest distance
        on_sp = via.T == dist[:, None]  # [N, D]
        nh_mask = jnp.any(s4[:, :, None] & on_sp[idx], axis=1)  # [P, D]
        has_route = s3.any(axis=1) & (metric < INF)

        out8 = jnp.concatenate(
            [
                jax.lax.bitcast_convert_type(metric, jnp.int8).ravel(),
                s3.astype(jnp.int8).ravel(),
                nh_mask.astype(jnp.int8).ravel(),
                has_route.astype(jnp.int8),
            ]
        )
        return out8

    jitted = jax.jit(pipeline)

    def run(gbuf, mbuf):
        out = np.asarray(jitted(gbuf, mbuf))  # exec + single small pull
        o = 0
        metric = out[o : o + 4 * p_cap].view(np.int32); o += 4 * p_cap
        s3 = out[o : o + pa].reshape(p_cap, a_cap).astype(bool); o += pa
        nh_mask = (
            out[o : o + p_cap * d_cap].reshape(p_cap, d_cap).astype(bool)
        )
        o += p_cap * d_cap
        has_route = out[o : o + p_cap].astype(bool)
        return metric, s3, nh_mask, has_route

    return run


@functools.lru_cache(maxsize=None)
def _jitted_sssp_batch():
    """vmapped multi-root SSSP (whole-fabric / benchmark path)."""
    import jax

    return jax.jit(
        jax.vmap(_sssp_kernel, in_axes=(None, None, None, None, 0))
    )


def sssp_all_pairs(graph: EllGraph, roots: Optional[np.ndarray] = None):
    """Batched SSSP from many roots — [R, N_cap] int32 distances."""
    import jax

    if roots is None:
        roots = np.arange(graph.n_nodes, dtype=np.int32)
    fn = _jitted_sssp_batch()
    args = jax.device_put(
        [
            graph.in_nbr,
            graph.in_w,
            graph.in_up,
            graph.node_overloaded,
            roots.astype(np.int32),
        ]
    )
    return fn(*args)


# ---------------------------------------------------------------------------
# solver
# ---------------------------------------------------------------------------

def _fast_path_eligible(entries) -> bool:
    """Device fast path covers IP + SP_ECMP announcements without prepend
    labels; anything else routes through the CPU oracle."""
    for entry in entries.values():
        if (
            entry.forwarding_type != PrefixForwardingType.IP
            or entry.forwarding_algorithm != PrefixForwardingAlgorithm.SP_ECMP
            or entry.prepend_label is not None
        ):
            return False
    return True


class TpuSpfSolver:
    """Drop-in replacement for SpfSolver.build_route_db with the hot path
    on device. Differentially tested against the CPU oracle."""

    def __init__(self, my_node_name: str, **solver_kwargs):
        self.my_node_name = my_node_name
        self.cpu = SpfSolver(my_node_name, **solver_kwargs)
        self._mirrors: dict[str, tuple[int, EllGraph]] = {}
        # host-side derived caches (root out-table, announcer matrix) and
        # the resident packed device buffer per (area, vantage)
        self._dev_graph: dict[tuple, tuple[int, tuple]] = {}
        self._dev_matrix: dict[str, tuple] = {}
        self._dev_buf: dict[tuple, tuple[np.ndarray, object]] = {}
        # LRU over foreign vantages: any-vantage ctrl queries must not
        # accumulate resident host+device buffers per queried node forever
        self._vantage_lru: list[tuple] = []
        self._partition = None  # (ps.generation, fast, slow)
        # per-vantage {(slot bits, metric) -> frozenset[NextHop]} — scoped so
        # one vantage's buffer churn cannot thrash another's hot path
        self._nh_set_cache: dict[str, dict] = {}
        self.last_device_stats: dict = {}

    # static-route passthroughs keep Decision actor backend-agnostic
    def update_static_unicast_routes(self, to_update, to_delete) -> None:
        self.cpu.update_static_unicast_routes(to_update, to_delete)

    def update_static_mpls_routes(self, to_update, to_delete) -> None:
        self.cpu.update_static_mpls_routes(to_update, to_delete)

    def create_route_for_prefix_or_get_static(
        self, my_node_name, area_link_states, prefix_state, prefix
    ):
        """Incremental per-prefix path (Decision's changed-prefix rebuild):
        single-prefix work has no batch to amortize a device launch over,
        so it delegates to the CPU oracle. The resident SPF tensors keep
        serving the full-rebuild path."""
        return self.cpu.create_route_for_prefix_or_get_static(
            my_node_name, area_link_states, prefix_state, prefix
        )

    @property
    def static_unicast_routes(self):
        return self.cpu.static_unicast_routes

    @property
    def static_mpls_routes(self):
        return self.cpu.static_mpls_routes

    _MAX_FOREIGN_VANTAGES = 4

    def _touch_foreign_vantage(self, gkey: tuple) -> None:
        lru = self._vantage_lru
        if gkey in lru:
            lru.remove(gkey)
        lru.append(gkey)
        while len(lru) > self._MAX_FOREIGN_VANTAGES:
            old = lru.pop(0)
            self._dev_graph.pop(old, None)
            self._dev_buf.pop(old, None)
            self._nh_set_cache.pop(old[1], None)

    def mirror(self, link_state: LinkState) -> EllGraph:
        """Device mirror, refreshed when the LinkState generation moves."""
        cached = self._mirrors.get(link_state.area)
        if cached is not None and cached[0] == link_state.generation:
            return cached[1]
        prev = cached[1] if cached is not None else None
        graph = build_ell(link_state, prev=prev)
        self._mirrors[link_state.area] = (link_state.generation, graph)
        return graph

    def build_route_db(
        self,
        my_node_name: str,
        area_link_states: dict[str, LinkState],
        prefix_state: PrefixState,
    ) -> Optional[DecisionRouteDb]:
        # multi-area: selection must be global across areas — CPU path
        # (single-area is the device-accelerated deployment this round)
        if len(area_link_states) != 1:
            return self.cpu.build_route_db(
                my_node_name, area_link_states, prefix_state
            )
        area, link_state = next(iter(area_link_states.items()))
        if not link_state.has_node(my_node_name):
            return None

        if self._partition is not None and self._partition[0] == prefix_state.generation:
            fast, slow = self._partition[1], self._partition[2]
        else:
            fast, slow = [], []
            for prefix, entries in prefix_state.prefixes().items():
                (fast if _fast_path_eligible(entries) else slow).append(prefix)
            self._partition = (prefix_state.generation, fast, slow)

        route_db = DecisionRouteDb()
        if fast:
            self._solve_fast(
                my_node_name, area, link_state, prefix_state, fast, route_db
            )

        # CPU oracle path for irregular prefixes + statics + MPLS
        self.cpu.best_routes_cache.clear()
        for prefix in slow:
            route = self.cpu.create_route_for_prefix(
                my_node_name, area_link_states, prefix_state, prefix
            )
            if route is not None:
                route_db.add_unicast_route(route)
        for prefix, entry in self.cpu.static_unicast_routes.items():
            if prefix not in route_db.unicast_routes:
                route_db.add_unicast_route(entry)
        if self.cpu.enable_node_segment_label:
            for entry in self.cpu._node_label_routes(
                my_node_name, area_link_states
            ).values():
                route_db.add_mpls_route(entry)
        if self.cpu.enable_adjacency_labels:
            for entry in self.cpu._adj_label_routes(my_node_name, area_link_states):
                route_db.add_mpls_route(entry)
        for entry in self.cpu.static_mpls_routes.values():
            route_db.add_mpls_route(entry)
        return route_db

    def _solve_fast(
        self,
        my_node_name: str,
        area: str,
        link_state: LinkState,
        prefix_state: PrefixState,
        prefixes: list[str],
        route_db: DecisionRouteDb,
    ) -> None:
        import jax

        graph = self.mirror(link_state)
        root_idx = graph.node_index[my_node_name]

        # root out-edge table, cached per (area, vantage, generation):
        # build_route_db serves any-vantage queries (ctrl API)
        gkey = (area, my_node_name)
        if my_node_name != self.my_node_name:
            self._touch_foreign_vantage(gkey)
        cached = self._dev_graph.get(gkey)
        if cached is None or cached[0] != link_state.generation:
            root_table = graph.out_table(root_idx)
            self._dev_graph[gkey] = (link_state.generation, root_table)
        root_nbr, root_w, root_up, links = self._dev_graph[gkey][1]

        # announcer matrix: keyed on prefix churn + node-index stability —
        # metric/link flaps that preserve the node set reuse it as-is
        mkey = (prefix_state.generation, graph.index_version)
        mcached = self._dev_matrix.get(area)
        if mcached is None or mcached[0] != mkey:
            matrix = build_prefix_matrix(
                prefix_state, graph.node_index, area, prefixes
            )
            self._dev_matrix[area] = (mkey, matrix)
        matrix = self._dev_matrix[area][1]

        # TWO packed input buffers (graph-per-vantage, announcer matrix),
        # each resident on device and re-uploaded only when its content
        # changed — the device link is bandwidth-bound, and topology churn
        # and prefix churn invalidate different halves
        gbuf = pack_graph_inputs(
            graph.in_nbr, graph.in_w, graph.in_up, graph.node_overloaded,
            root_idx, root_nbr, root_w, root_up,
        )
        dev_cached = self._dev_buf.get(gkey)
        if (
            dev_cached is None
            or dev_cached[0].shape != gbuf.shape
            or not np.array_equal(dev_cached[0], gbuf)
        ):
            self._dev_buf[gkey] = (gbuf, jax.device_put(gbuf))
            # link objects may have changed — this vantage's sets only
            self._nh_set_cache.pop(my_node_name, None)
        dev_gbuf = self._dev_buf[gkey][1]

        mbuf = pack_matrix_inputs(matrix, graph.node_overloaded)
        mbuf_key = ("matrix", area)
        dev_mcached = self._dev_buf.get(mbuf_key)
        if (
            dev_mcached is None
            or dev_mcached[0].shape != mbuf.shape
            or not np.array_equal(dev_mcached[0], mbuf)
        ):
            self._dev_buf[mbuf_key] = (mbuf, jax.device_put(mbuf))
        dev_mbuf = self._dev_buf[mbuf_key][1]

        d_cap = root_nbr.shape[0]
        p_cap, a_cap = matrix.ann_node.shape
        run = _jitted_packed_pipeline(
            graph.n_cap, graph.k_cap, d_cap, p_cap, a_cap
        )
        metric_np, s3_np, nh_np, has_np = run(dev_gbuf, dev_mbuf)
        self.last_device_stats = {
            "n_cap": graph.n_cap,
            "k_cap": graph.k_cap,
            "n_prefixes": len(matrix.prefix_list),
        }

        self._materialize(
            my_node_name,
            prefix_state,
            matrix,
            links,
            root_idx,
            metric_np,
            s3_np,
            nh_np,
            has_np,
            route_db,
        )

    def _materialize(
        self,
        my_node_name: str,
        prefix_state: PrefixState,
        matrix: PrefixMatrix,
        links: list,
        root_idx: int,
        metric: np.ndarray,
        s3: np.ndarray,
        nh_mask: np.ndarray,
        has_route: np.ndarray,
        route_db: DecisionRouteDb,
    ) -> None:
        """Host materialization of device outputs into RibUnicastEntry.

        All route-level filters run vectorized over numpy; the Python loop
        only constructs entries for surviving rows, with next-hop sets
        memoized per (slot pattern, metric) — route fan-outs repeat heavily
        across prefixes, so the cache collapses most construction cost.
        """
        p_n = len(matrix.prefix_list)
        ok = has_route[:p_n].copy()
        # v4 gate
        if not (self.cpu.enable_v4 or self.cpu.v4_over_v6_nexthop):
            ok &= ~matrix.is_v4[:p_n]
        s3n = s3[:p_n]
        # self-advertised skip (fast path has no prepend labels)
        ok &= ~(s3n & (matrix.ann_node[:p_n] == root_idx)).any(axis=1)
        # min-nexthop threshold: max over selected announcers vs nh count
        eff_min = np.where(s3n, matrix.min_nexthop[:p_n], -1).max(axis=1)
        nh_count = nh_mask[:p_n].sum(axis=1)
        ok &= (eff_min <= nh_count) & (nh_count > 0)

        d_range = range(nh_mask.shape[1])
        nh_cache = self._nh_set_cache.setdefault(my_node_name, {})
        for p in np.flatnonzero(ok):
            prefix = matrix.prefix_list[p]
            row = s3n[p]
            selected = [
                na for a, na in enumerate(matrix.node_areas[p]) if row[a]
            ]
            if not selected:
                continue
            m = int(metric[p])
            bits = tuple(d for d in d_range if nh_mask[p, d])
            # slot indices are root-relative; the cache dict is per-vantage
            key = (bits, m)
            nexthops = nh_cache.get(key)
            if nexthops is None:
                nexthops = frozenset(
                    NextHop(
                        address=links[d].nh_v6_from_node(my_node_name),
                        if_name=links[d].iface_from_node(my_node_name),
                        metric=m,
                        area=links[d].area,
                        neighbor_node_name=links[d].other_node(my_node_name),
                    )
                    for d in bits
                )
                nh_cache[key] = nexthops
            best = (
                selected[0]
                if len(selected) == 1
                else select_best_node_area(set(selected), my_node_name)
            )
            entries = prefix_state.entries_for(prefix)
            route_db.add_unicast_route(
                RibUnicastEntry(
                    prefix=prefix,
                    nexthops=nexthops,
                    best_prefix_entry=entries[best],
                    best_node_area=best,
                    igp_cost=m,
                )
            )
