"""Static-analysis suite for the openr_tpu actor/trace invariants.

Run `python -m tools.lint` (or `--all` to add ruff) — see
docs/StaticAnalysis.md for the checker catalog and suppression format.
"""
