"""OpenMetrics / Prometheus exposition for the counters fabric.

Zero-dependency: the scrape endpoint is a minimal asyncio HTTP server
(no aiohttp/prometheus_client in the image) served from the Monitor's
event base when `monitor_config.metrics_port` is set. It renders the
entire `CounterRegistry` — plain counters/gauges plus the
p50/p95/p99 windows from `_aggregate_windows` — as exposition text an
off-the-shelf Prometheus scraper accepts.

Name mapping: the fabric uses fb303-style dotted names
(`decision.spf_ms`, `kvstore.<node>.updated_key_vals`); Prometheus
identifiers are `[a-zA-Z_:][a-zA-Z0-9_:]*`. `normalize_metric_name`
maps one to the other deterministically (dots and other invalid bytes
become `_`, everything is prefixed `openr_tpu_`). The mapping is
lossy — `a.b` and `a_b` collide — so `tools/check_metric_names.py`
statically verifies at lint time that every counter name bumped in the
codebase normalizes to a unique identifier.

Stat windows render as one summary family per stat with a
`window="60|600|3600"` label and `quantile` samples, plus `_sum`,
`_count`, and sibling `_max` / `_truncated` gauge families (`avg` is
derivable as sum/count and is not exported).
"""

from __future__ import annotations

import asyncio
import platform
import re
import sys
import time
from typing import Optional

from openr_tpu.runtime.counters import CounterRegistry, counters

METRIC_PREFIX = "openr_tpu_"

# exposition identifier grammar (Prometheus data model)
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_INVALID_CHARS_RE = re.compile(r"[^a-zA-Z0-9_:]")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{([^{}]*)\})?"
    r" (-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|Inf)|NaN|\+Inf)$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

# the stat-window quantiles _aggregate_windows computes
_QUANTILES = (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"))


def normalize_metric_name(name: str) -> str:
    """Dotted fabric name -> exposition identifier. Deterministic and
    total (any input maps to a valid identifier); NOT injective — the
    CI checker guards collisions."""
    return METRIC_PREFIX + _INVALID_CHARS_RE.sub("_", name)


def is_valid_metric_name(name: str) -> bool:
    return bool(_NAME_RE.match(name))


def _fmt(v: float) -> str:
    # repr round-trips floats exactly: float(_fmt(v)) == v
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def render_exposition(
    counters_snap: dict[str, float], stats_snap: dict[str, dict]
) -> str:
    """(counters, stat-windows) -> exposition text. Input shape is
    exactly CounterRegistry.export_snapshot()'s output."""
    lines: list[str] = []
    emitted: set[str] = set()

    def family(name: str, mtype: str, help_text: str) -> bool:
        # one HELP/TYPE block per family; a post-normalization collision
        # (guarded at lint time by tools/check_metric_names.py) is
        # dropped rather than emitting an invalid duplicate family
        if name in emitted:
            return False
        emitted.add(name)
        lines.append(f"# HELP {name} {_escape_help(help_text)}")
        lines.append(f"# TYPE {name} {mtype}")
        return True

    for key in sorted(counters_snap):
        name = normalize_metric_name(key)
        if family(name, "gauge", f"openr_tpu counter '{key}'"):
            lines.append(f"{name} {_fmt(counters_snap[key])}")

    for key in sorted(stats_snap):
        name = normalize_metric_name(key)
        windows = stats_snap[key]
        if family(name, "summary", f"openr_tpu stat '{key}' (windowed)"):
            for w in sorted(windows, key=int):
                agg = windows[w]
                for q, field in _QUANTILES:
                    lines.append(
                        f'{name}{{window="{w}",quantile="{q}"}} '
                        f"{_fmt(agg[field])}"
                    )
                lines.append(f'{name}_sum{{window="{w}"}} {_fmt(agg["sum"])}')
                lines.append(
                    f'{name}_count{{window="{w}"}} {_fmt(agg["count"])}'
                )
        for suffix, field, help_text in (
            ("_max", "max", "window maximum"),
            ("_truncated", "truncated", "1 when the sample ring wrapped "
             "before the window cutoff"),
        ):
            if family(
                name + suffix, "gauge",
                f"openr_tpu stat '{key}' {help_text}",
            ):
                for w in sorted(windows, key=int):
                    lines.append(
                        f'{name}{suffix}{{window="{w}"}} '
                        f"{_fmt(float(windows[w][field]))}"
                    )

    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def parse_exposition(text: str) -> dict[tuple, float]:
    """Strict line parse of exposition text back into
    {(name, ((label, value), ...)): float}. Raises ValueError on any
    malformed sample line — the round-trip test uses this to prove the
    endpoint serves valid text for 100% of registry entries."""
    out: dict[tuple, float] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"malformed exposition line: {line!r}")
        name, label_blob, value = m.group(1), m.group(2), m.group(3)
        labels: tuple = ()
        if label_blob:
            pairs = _LABEL_RE.findall(label_blob)
            # reject label blobs the pair grammar didn't fully consume
            if _LABEL_RE.sub("", label_blob).strip(", ") != "":
                raise ValueError(f"malformed labels: {line!r}")
            labels = tuple(sorted(pairs))
        out[(name, labels)] = float(value)
    return out


def _label_escape(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def build_info_labels() -> dict[str, str]:
    """Identity labels for the `openr_tpu_build_info` info gauge:
    package version, jax/jaxlib fingerprint, and the active backend.
    Passive on jax — reads versions only if something else already
    imported it (device_stats._jax discipline), so a scrape never
    drags the device toolchain into a control-plane-only process."""
    import openr_tpu
    from openr_tpu.runtime import device_stats

    jax = device_stats._jax(allow_import=False)
    jaxlib = sys.modules.get("jaxlib")
    return {
        "version": openr_tpu.__version__,
        "python": platform.python_version(),
        "jax": getattr(jax, "__version__", "absent") if jax else "absent",
        "jaxlib": getattr(jaxlib, "__version__", "absent")
        if jaxlib
        else "absent",
        "backend": device_stats.collect_device_stats()["backend"],
    }


def render_build_info() -> str:
    """The prometheus info-gauge idiom: constant value 1, identity in
    the labels — `openr_tpu_build_info{version=...,jax=...} 1`."""
    labels = ",".join(
        f'{k}="{_label_escape(v)}"'
        for k, v in sorted(build_info_labels().items())
    )
    name = METRIC_PREFIX + "build_info"
    return (
        f"# HELP {name} build/runtime identity (constant 1)\n"
        f"# TYPE {name} gauge\n"
        f"{name}{{{labels}}} 1\n"
    )


def render_registry(registry: Optional[CounterRegistry] = None) -> str:
    reg = registry if registry is not None else counters
    counters_snap, stats_snap = reg.export_snapshot()
    return render_build_info() + render_exposition(
        counters_snap, stats_snap
    )


class MetricsExporter:
    """Minimal asyncio HTTP/1.0 scrape server: GET /metrics -> the
    registry exposition. Runs on the Monitor's event loop; one render
    per scrape, no background work between scrapes."""

    def __init__(
        self,
        registry: Optional[CounterRegistry] = None,
        listen_addr: str = "127.0.0.1",
        port: int = 0,
    ):
        self._registry = registry if registry is not None else counters
        self._listen_addr = listen_addr
        self._requested_port = port
        self._server: Optional[asyncio.base_events.Server] = None
        self.port: int = 0  # bound port (differs from requested when 0)

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self._listen_addr, self._requested_port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            request = await asyncio.wait_for(reader.readline(), timeout=5.0)
            parts = request.decode("latin-1").split()
            # drain headers; scrape requests carry no body
            while True:
                line = await asyncio.wait_for(reader.readline(), timeout=5.0)
                if line in (b"\r\n", b"\n", b""):
                    break
            if len(parts) >= 2 and parts[0] == "GET" and (
                parts[1] == "/metrics" or parts[1].startswith("/metrics?")
            ):
                t0 = time.perf_counter()
                body = render_registry(self._registry).encode()
                status = "200 OK"
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                counters.increment("monitor.metrics_scrapes")
                counters.add_stat_value(
                    "monitor.metrics_scrape_ms",
                    (time.perf_counter() - t0) * 1000.0,
                )
            else:
                body = b"openr_tpu exporter: scrape /metrics\n"
                status = "404 Not Found"
                ctype = "text/plain; charset=utf-8"
            writer.write(
                (
                    f"HTTP/1.0 {status}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    "Connection: close\r\n\r\n"
                ).encode()
                + body
            )
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):  # pragma: no cover
                pass
