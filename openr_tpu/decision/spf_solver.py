"""CPU route-computation oracle.

Role of the reference's openr/decision/SpfSolver.{h,cpp}: per-prefix route
computation — reachability-filter announcers (SpfSolver.cpp:230-244) ->
select_best_routes (:648-769) -> drained-node filter (:709-731) -> per-area
forwarding-algorithm switch SP_ECMP / UCMP / KSP2_ED_ECMP (:356-443) ->
get_next_hops_with_metric (:1043-1089) -> get_next_hops (:1165-1285,
neighbor-link enumeration, shortest-only filter, MPLS PUSH/SWAP/PHP label
construction, UCMP weight attach) -> add_best_paths (:975-1041, min-nexthop
threshold, self-prepend-label next hops). build_route_db (:460-646) loops
every prefix + node-segment-label MPLS routes + adj-label routes + statics.

Scope notes vs the reference (documented deviations):
  - Best-route selection is always metric-based SHORTEST_DISTANCE (the
    reference's enableBestRouteSelection_ path); the legacy BGP
    MetricVector comparison path (:709-769) serves the closed-source BGP
    plugin and is not replicated.
  - SR policy rules default (getRouteComputationRules builds per-area
    forwarding type/algo as the min over best entries, LsdbUtil.cpp:379).

This is the correctness oracle for decision/tpu_solver.py; both are pure
functions of (areaLinkStates, prefixState) and are differentially tested.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional

from openr_tpu.decision.link_state import LinkState, NodeUcmpResult, path_a_in_path_b
from openr_tpu.decision.prefix_state import PrefixEntries, PrefixState
from openr_tpu.decision.rib import (
    DecisionRouteDb,
    MplsAction,
    MplsActionCode,
    NextHop,
    RibMplsEntry,
    RibUnicastEntry,
    is_mpls_label_valid,
)
from openr_tpu.types import (
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
    parse_prefix,
)

INF = float("inf")

NodeAndArea = tuple  # (node, area)


@dataclass
class RouteSelectionResult:
    """ref SpfSolver.h:30-55."""

    all_node_areas: set = field(default_factory=set)
    best_node_area: NodeAndArea = ("", "")
    success: bool = False

    def has_node(self, node: str) -> bool:
        return any(n == node for n, _ in self.all_node_areas)


def select_routes(prefix_entries: PrefixEntries) -> set:
    """SHORTEST_DISTANCE selection (ref LsdbUtil.cpp selectRoutes:842):
    best (path_preference desc, source_preference desc), then min advertised
    distance."""
    best_tuple = None
    node_area_set: set = set()
    for key, entry in prefix_entries.items():
        t = (entry.metrics.path_preference, entry.metrics.source_preference)
        if best_tuple is not None and t < best_tuple:
            continue
        if best_tuple is None or t > best_tuple:
            best_tuple = t
            node_area_set.clear()
        node_area_set.add(key)
    # shortest advertised distance among preference winners
    best_dist = None
    out: set = set()
    for key in node_area_set:
        d = prefix_entries[key].metrics.distance
        if best_dist is not None and d > best_dist:
            continue
        if best_dist is None or d < best_dist:
            best_dist = d
            out.clear()
        out.add(key)
    return out


def select_best_node_area(all_node_areas: set, my_node: str) -> NodeAndArea:
    """ref LsdbUtil.cpp:758 — deterministic min, preferring self."""
    best = min(all_node_areas)
    for node_area in all_node_areas:
        if node_area[0] == my_node:
            return node_area
    return best


class SpfSolver:
    """ref SpfSolver.h:101."""

    def __init__(
        self,
        my_node_name: str,
        enable_v4: bool = True,
        enable_node_segment_label: bool = False,
        enable_adjacency_labels: bool = False,
        enable_ucmp: bool = False,
        enable_best_route_selection: bool = True,
        v4_over_v6_nexthop: bool = False,
        enable_lfa: bool = False,
    ):
        self.my_node_name = my_node_name
        self.enable_v4 = enable_v4
        self.enable_node_segment_label = enable_node_segment_label
        self.enable_adjacency_labels = enable_adjacency_labels
        self.enable_ucmp = enable_ucmp
        self.enable_lfa = enable_lfa
        self.enable_best_route_selection = enable_best_route_selection
        self.v4_over_v6_nexthop = v4_over_v6_nexthop
        self.static_unicast_routes: dict[str, RibUnicastEntry] = {}
        self.static_mpls_routes: dict[int, RibMplsEntry] = {}
        self.best_routes_cache: dict[str, RouteSelectionResult] = {}
        # optional accelerator hook for resolve_ucmp_weights (the TPU
        # solver installs a device-backed one): called with
        # (my_node_name, area, link_state, dst_weights,
        # use_prefix_weight) and returns the root's NodeUcmpResult (or
        # None when UCMP is inapplicable); NotImplemented falls back to
        # the host heap walk
        self.ucmp_resolver = None

    # -- static routes (ref SpfSolver.cpp:118-174) -------------------------

    def update_static_unicast_routes(
        self,
        to_update: dict[str, RibUnicastEntry],
        to_delete: list[str],
    ) -> None:
        for prefix, entry in to_update.items():
            self.static_unicast_routes[prefix] = entry
        for prefix in to_delete:
            self.static_unicast_routes.pop(prefix, None)

    def update_static_mpls_routes(
        self, to_update: dict[int, RibMplsEntry], to_delete: list[int]
    ) -> None:
        for label, entry in to_update.items():
            self.static_mpls_routes[label] = entry
        for label in to_delete:
            self.static_mpls_routes.pop(label, None)

    # -- full build (ref SpfSolver.cpp:460-646) ----------------------------

    def build_route_db(
        self,
        my_node_name: str,
        area_link_states: dict[str, LinkState],
        prefix_state: PrefixState,
    ) -> Optional[DecisionRouteDb]:
        if not any(ls.has_node(my_node_name) for ls in area_link_states.values()):
            return None
        route_db = DecisionRouteDb()
        self.best_routes_cache.clear()

        for prefix in prefix_state.prefixes():
            route = self.create_route_for_prefix(
                my_node_name, area_link_states, prefix_state, prefix
            )
            if route is not None:
                route_db.add_unicast_route(route)

        for prefix, entry in self.static_unicast_routes.items():
            if prefix not in route_db.unicast_routes:
                route_db.add_unicast_route(entry)

        if self.enable_node_segment_label:
            for label, entry in self._node_label_routes(
                my_node_name, area_link_states
            ).items():
                route_db.add_mpls_route(entry)

        if self.enable_adjacency_labels:
            for entry in self._adj_label_routes(my_node_name, area_link_states):
                route_db.add_mpls_route(entry)

        for entry in self.static_mpls_routes.values():
            route_db.add_mpls_route(entry)

        return route_db

    def create_route_for_prefix_or_get_static(
        self,
        my_node_name: str,
        area_link_states: dict[str, LinkState],
        prefix_state: PrefixState,
        prefix: str,
    ) -> Optional[RibUnicastEntry]:
        """Incremental-path entry (ref SpfSolver.cpp:175-195)."""
        route = self.create_route_for_prefix(
            my_node_name, area_link_states, prefix_state, prefix
        )
        if route is not None:
            return route
        return self.static_unicast_routes.get(prefix)

    def create_route_for_prefix(
        self,
        my_node_name: str,
        area_link_states: dict[str, LinkState],
        prefix_state: PrefixState,
        prefix: str,
    ) -> Optional[RibUnicastEntry]:
        """ref SpfSolver.cpp:196-455."""
        net = parse_prefix(prefix)
        is_v4 = net.version == 4
        if is_v4 and not self.enable_v4 and not self.v4_over_v6_nexthop:
            return None

        all_entries = prefix_state.entries_for(prefix)
        if not all_entries:
            return None
        self.best_routes_cache.pop(prefix, None)

        # reachability filter: drop announcers unreachable in their area
        # (ref SpfSolver.cpp:230-244)
        prefix_entries: PrefixEntries = dict(all_entries)
        for area, link_state in area_link_states.items():
            spf = link_state.get_spf_result(my_node_name)
            for node_area in list(prefix_entries):
                node, pfx_area = node_area
                if pfx_area == area and node not in spf:
                    del prefix_entries[node_area]
        if not prefix_entries:
            return None

        # self-prepend-label flag (ref SpfSolver.cpp:262-270)
        has_self_prepend_label = True
        for (node, _), entry in prefix_entries.items():
            if node == my_node_name:
                has_self_prepend_label &= entry.prepend_label is not None

        selection = self.select_best_routes(
            my_node_name, prefix_entries, area_link_states
        )
        if not selection.success or not selection.all_node_areas:
            return None
        self.best_routes_cache[prefix] = selection

        # skip route for a prefix advertised by self, unless it carries a
        # prepend label (ref SpfSolver.cpp:330-344)
        if selection.has_node(my_node_name) and not has_self_prepend_label:
            return None

        # per-area forwarding rules = min over best entries in area
        # (ref LsdbUtil.cpp:379-413)
        total_next_hops: set[NextHop] = set()
        ucmp_weight: Optional[int] = None
        shortest_metric = INF
        lfa_candidates: list = []
        for area, link_state in area_link_states.items():
            rules = self._area_forwarding_rules(area, prefix_entries, selection)
            if rules is None:
                continue
            fwd_type, fwd_algo = rules
            if fwd_algo in (
                PrefixForwardingAlgorithm.SP_ECMP,
                PrefixForwardingAlgorithm.SP_UCMP_ADJ_WEIGHT_PROPAGATION,
                PrefixForwardingAlgorithm.SP_UCMP_PREFIX_WEIGHT_PROPAGATION,
            ):
                best_metric, nhs, area_ucmp = self._select_best_paths_spf(
                    my_node_name,
                    prefix,
                    selection,
                    prefix_entries,
                    fwd_type,
                    area,
                    link_state,
                    fwd_algo,
                    is_v4,
                )
                if (
                    self.enable_lfa
                    and fwd_type == PrefixForwardingType.IP
                    and nhs
                    and best_metric < INF
                ):
                    lfa_candidates.extend(
                        self._lfa_candidates(
                            my_node_name,
                            selection,
                            area,
                            link_state,
                            int(best_metric),
                        )
                    )
                # only keep next hops from areas with the shortest IGP metric
                if shortest_metric >= best_metric:
                    if shortest_metric > best_metric:
                        shortest_metric = best_metric
                        total_next_hops.clear()
                        ucmp_weight = None
                    total_next_hops.update(nhs)
                    if ucmp_weight is None:
                        ucmp_weight = area_ucmp
                    elif area_ucmp is not None:
                        ucmp_weight += area_ucmp
            elif fwd_algo == PrefixForwardingAlgorithm.KSP2_ED_ECMP:
                total_next_hops.update(
                    self._select_best_paths_ksp2(
                        my_node_name,
                        prefix,
                        selection,
                        prefix_entries,
                        fwd_type,
                        area,
                        link_state,
                        is_v4,
                    )
                )

        route = self._add_best_paths(
            my_node_name,
            prefix,
            selection,
            prefix_entries,
            total_next_hops,
            0 if shortest_metric == INF else int(shortest_metric),
            ucmp_weight,
        )
        if route is not None and lfa_candidates:
            primary = {
                (nh.if_name, nh.neighbor_node_name) for nh in route.nexthops
            }
            cands = [
                c
                for c in lfa_candidates
                if (
                    c[3].iface_from_node(my_node_name),
                    c[3].other_node(my_node_name),
                )
                not in primary
            ]
            if cands:
                alt_metric, _, _, link = min(cands)
                lfa = NextHop(
                    address=link.nh_from_node(
                        my_node_name,
                        is_v4 and not self.v4_over_v6_nexthop,
                    ),
                    if_name=link.iface_from_node(my_node_name),
                    metric=alt_metric,
                    area=link.area,
                    neighbor_node_name=link.other_node(my_node_name),
                )
                route = replace(route, lfa_nexthops=frozenset({lfa}))
        return route

    # -- best-route selection (ref SpfSolver.cpp:648-707) ------------------

    def select_best_routes(
        self,
        my_node_name: str,
        prefix_entries: PrefixEntries,
        area_link_states: dict[str, LinkState],
    ) -> RouteSelectionResult:
        assert prefix_entries, "no prefixes for best route selection"
        ret = RouteSelectionResult()
        if self.enable_best_route_selection:
            ret.all_node_areas = select_routes(prefix_entries)
            ret.best_node_area = select_best_node_area(
                ret.all_node_areas, my_node_name
            )
            ret.success = True
        else:
            ret.all_node_areas = set(prefix_entries)
            ret.best_node_area = min(ret.all_node_areas)
            ret.success = True
        return self._maybe_filter_drained_nodes(ret, area_link_states)

    def _maybe_filter_drained_nodes(
        self,
        result: RouteSelectionResult,
        area_link_states: dict[str, LinkState],
    ) -> RouteSelectionResult:
        """Drop soft-drained announcers; if ALL are drained keep the
        unfiltered set (ref SpfSolver.cpp:709-731)."""
        filtered = {
            (node, area)
            for node, area in result.all_node_areas
            if not area_link_states[area].is_node_overloaded(node)
        }
        if not filtered:
            return result
        out = RouteSelectionResult(
            all_node_areas=filtered,
            best_node_area=result.best_node_area,
            success=result.success,
        )
        if result.best_node_area not in filtered:
            out.best_node_area = min(filtered)
        return out

    def _area_forwarding_rules(
        self,
        area: str,
        prefix_entries: PrefixEntries,
        selection: RouteSelectionResult,
    ) -> Optional[tuple[PrefixForwardingType, PrefixForwardingAlgorithm]]:
        rules = None
        for node_area, entry in prefix_entries.items():
            if node_area not in selection.all_node_areas or node_area[1] != area:
                continue
            if rules is None:
                rules = (entry.forwarding_type, entry.forwarding_algorithm)
            else:
                rules = (
                    min(rules[0], entry.forwarding_type),
                    min(rules[1], entry.forwarding_algorithm),
                )
        return rules

    # -- SPF path selection (ref SpfSolver.cpp:771-845) --------------------

    def _select_best_paths_spf(
        self,
        my_node_name: str,
        prefix: str,
        selection: RouteSelectionResult,
        prefix_entries: PrefixEntries,
        fwd_type: PrefixForwardingType,
        area: str,
        link_state: LinkState,
        fwd_algo: PrefixForwardingAlgorithm,
        is_v4: bool,
    ) -> tuple[float, set[NextHop], Optional[int]]:
        per_destination = fwd_type == PrefixForwardingType.SR_MPLS

        # self-originated SR_MPLS prefix with prepend label: don't route to
        # self (ref SpfSolver.cpp:796-808)
        dst_node_areas = set(selection.all_node_areas)
        if selection.has_node(my_node_name) and per_destination:
            for node_area, entry in prefix_entries.items():
                if node_area[0] == my_node_name and entry.prepend_label is not None:
                    dst_node_areas.discard(node_area)
                    break

        min_metric, next_hop_nodes = self.get_next_hops_with_metric(
            my_node_name, dst_node_areas, per_destination, link_state
        )
        if not next_hop_nodes:
            return min_metric, set(), None

        ucmp_result = self._get_node_ucmp_result(
            my_node_name,
            fwd_algo,
            area,
            link_state,
            prefix_entries,
            selection.all_node_areas,
            min_metric,
        )
        ucmp_weight = ucmp_result.weight if ucmp_result is not None else None

        nhs = self.get_next_hops(
            my_node_name,
            selection.all_node_areas,
            is_v4,
            per_destination,
            min_metric,
            next_hop_nodes,
            None,
            area,
            link_state,
            prefix_entries,
            ucmp_result,
        )
        return min_metric, nhs, ucmp_weight

    def get_next_hops_with_metric(
        self,
        my_node_name: str,
        dst_node_areas: set,
        per_destination: bool,
        link_state: LinkState,
    ) -> tuple[float, dict[tuple[str, str], int]]:
        """ref SpfSolver.cpp:1043-1089 — returns (min metric to the
        destination set, map (next-hop node, dst-or-'') -> distance from
        that next hop to the destination)."""
        spf = link_state.get_spf_result(my_node_name)
        shortest_metric = INF
        min_cost_nodes: set[str] = set()
        for dst_node, _ in dst_node_areas:
            node = spf.get(dst_node)
            if node is None:
                continue
            if shortest_metric >= node.metric:
                if shortest_metric > node.metric:
                    shortest_metric = node.metric
                    min_cost_nodes.clear()
                min_cost_nodes.add(dst_node)

        next_hop_nodes: dict[tuple[str, str], int] = {}
        for dst_node in min_cost_nodes:
            dst_ref = dst_node if per_destination else ""
            for nh_name in spf[dst_node].next_hops:
                next_hop_nodes[(nh_name, dst_ref)] = int(shortest_metric) - (
                    link_state.get_metric_from_a_to_b(my_node_name, nh_name) or 0
                )
        return shortest_metric, next_hop_nodes

    def get_next_hops(
        self,
        my_node_name: str,
        dst_node_areas: set,
        is_v4: bool,
        per_destination: bool,
        min_metric: float,
        next_hop_nodes: dict[tuple[str, str], int],
        swap_label: Optional[int],
        area: str,
        link_state: LinkState,
        prefix_entries: Optional[PrefixEntries] = None,
        ucmp_result: Optional[NodeUcmpResult] = None,
    ) -> set[NextHop]:
        """ref SpfSolver.cpp getNextHopsThrift:1165-1285."""
        assert next_hop_nodes
        next_hops: set[NextHop] = set()
        dst_iter = sorted(dst_node_areas) if per_destination else [("", "")]
        for link in link_state.links_from_node(my_node_name):
            for dst_node, dst_area in dst_iter:
                if dst_area and area != dst_area:
                    continue
                neighbor = link.other_node(my_node_name)
                dist_to_dst = next_hop_nodes.get((neighbor, dst_node))
                if dist_to_dst is None or not link.is_up():
                    continue
                # don't route via another destination that isn't this dst
                if (
                    dst_node
                    and (neighbor, area) in dst_node_areas
                    and neighbor != dst_node
                ):
                    continue
                dist_over_link = link.metric_from_node(my_node_name) + dist_to_dst
                if dist_over_link != min_metric:
                    continue  # not shortest

                mpls_action: Optional[MplsAction] = None
                if swap_label is not None:
                    nh_is_dst = (neighbor, area) in dst_node_areas
                    mpls_action = MplsAction(
                        MplsActionCode.PHP if nh_is_dst else MplsActionCode.SWAP,
                        None if nh_is_dst else swap_label,
                    )
                if dst_node:
                    push_labels: list[int] = []
                    entry = prefix_entries.get((dst_node, area)) if prefix_entries else None
                    if entry is not None and entry.prepend_label is not None:
                        if not is_mpls_label_valid(entry.prepend_label):
                            continue
                        push_labels.append(entry.prepend_label)
                    if dst_node != neighbor:
                        node_label = (
                            link_state.get_adjacency_databases()[dst_node].node_label
                        )
                        if not is_mpls_label_valid(node_label):
                            continue
                        push_labels.append(node_label)
                    if push_labels:
                        assert mpls_action is None
                        mpls_action = MplsAction(
                            MplsActionCode.PUSH, None, tuple(push_labels)
                        )

                weight = 0
                if ucmp_result is not None:
                    nh_link = ucmp_result.next_hop_links.get(
                        link.iface_from_node(my_node_name)
                    )
                    if nh_link is not None:
                        weight = nh_link.weight

                next_hops.add(
                    NextHop(
                        address=link.nh_from_node(
                            my_node_name,
                            is_v4 and not self.v4_over_v6_nexthop,
                        ),
                        if_name=link.iface_from_node(my_node_name),
                        metric=int(dist_over_link),
                        mpls_action=mpls_action,
                        area=link.area,
                        neighbor_node_name=neighbor,
                        weight=weight,
                    )
                )
        return next_hops

    # -- LFA fast-reroute alternates (rfc5286) -----------------------------

    def _lfa_candidates(
        self,
        my_node_name: str,
        selection: RouteSelectionResult,
        area: str,
        link_state: LinkState,
        area_metric: int,
    ) -> list:
        """Loop-free alternate candidates for one area: every up link to a
        neighbor N satisfying dist_N(P) < dist_N(self) + dist_self(P),
        where dist_N(P) = min over the selected announcers of N's own
        distance. Strict inequality guarantees every shortest N->P path
        avoids this node (a path through self costs at least the RHS), so
        pre-installing N as a backup cannot loop. Overloaded neighbors are
        skipped unless the neighbor is itself a selected destination
        (drained nodes must not pick up transit, but a direct link to the
        destination is fine) — mirroring the transit-drain rule runSpf
        applies (link_state.py run_spf; ref LinkState.cpp:870-876).

        Returns (alt_metric, area, link_order, link) tuples; the caller
        filters out primaries, keeps the global minimum and materializes
        that one winner as a NextHop. The TPU path (tpu_solver.py)
        computes the same predicate on device from its per-neighbor
        distance fields and is differentially tested against this oracle
        (tests/test_lfa.py)."""
        dsts = [n for n, a in selection.all_node_areas if a == area]
        if not dsts:
            return []
        out = []
        for order, link in enumerate(
            link_state.ordered_links_from_node(my_node_name)
        ):
            if not link.is_up():
                continue
            neighbor = link.other_node(my_node_name)
            n_is_dst = neighbor in dsts
            if link_state.is_node_overloaded(neighbor) and not n_is_dst:
                continue
            if n_is_dst:
                # the neighbor announces the prefix itself: trivially
                # loop-free, alternate cost = the link metric
                dist_np = 0
            else:
                spf_n = link_state.get_spf_result(neighbor)
                dist_np = min(
                    (spf_n[d].metric for d in dsts if d in spf_n),
                    default=None,
                )
                if dist_np is None:
                    continue
                root_res = spf_n.get(my_node_name)
                dist_nr = INF if root_res is None else root_res.metric
                if not dist_np < dist_nr + area_metric:
                    continue
            alt_metric = link.metric_from_node(my_node_name) + dist_np
            out.append((alt_metric, area, order, link))
        return out

    # -- KSP2 (ref SpfSolver.cpp:847-973) ----------------------------------

    def _select_best_paths_ksp2(
        self,
        my_node_name: str,
        prefix: str,
        selection: RouteSelectionResult,
        prefix_entries: PrefixEntries,
        fwd_type: PrefixForwardingType,
        area: str,
        link_state: LinkState,
        is_v4: bool,
    ) -> set[NextHop]:
        next_hops: set[NextHop] = set()
        if fwd_type != PrefixForwardingType.SR_MPLS:
            return next_hops  # incompatible forwarding type

        paths = []
        for node, best_area in sorted(selection.all_node_areas):
            if node == my_node_name and best_area == area:
                continue
            paths.extend(link_state.get_kth_paths(my_node_name, node, 1))
        first_count = len(paths)
        for node, best_area in sorted(selection.all_node_areas):
            if best_area != area:
                continue
            for sec_path in link_state.get_kth_paths(my_node_name, node, 2):
                # avoid double-spray: drop 2nd paths containing a 1st path
                if not any(
                    path_a_in_path_b(paths[i], sec_path) for i in range(first_count)
                ):
                    paths.append(sec_path)
        if not paths:
            return next_hops

        adj_dbs = link_state.get_adjacency_databases()
        for path in paths:
            cost = 0
            labels: list[int] = []  # stack, last = outermost
            invalid = False
            next_node = my_node_name
            for link in path:
                cost += link.metric_from_node(next_node)
                next_node = link.other_node(next_node)
                node_label = adj_dbs[next_node].node_label
                labels.insert(0, node_label)
                if not is_mpls_label_valid(node_label):
                    invalid = True
            if invalid:
                continue
            labels.pop()  # PHP: drop first-hop node's label... (see note)
            # NOTE ref SpfSolver.cpp:940 pops the *last* element of the
            # front-pushed list == the first node on the path (PHP).
            entry = prefix_entries.get((next_node, area))
            if entry is not None and entry.prepend_label is not None:
                labels.insert(0, entry.prepend_label)  # bottom of stack

            first_link = path[0]
            mpls_action = (
                MplsAction(MplsActionCode.PUSH, None, tuple(labels))
                if labels
                else None
            )
            next_hops.add(
                NextHop(
                    address=first_link.nh_v6_from_node(my_node_name),
                    if_name=first_link.iface_from_node(my_node_name),
                    metric=cost,
                    mpls_action=mpls_action,
                    area=first_link.area,
                    neighbor_node_name=first_link.other_node(my_node_name),
                )
            )
        return next_hops

    # -- final assembly (ref SpfSolver.cpp:975-1041) -----------------------

    def _add_best_paths(
        self,
        my_node_name: str,
        prefix: str,
        selection: RouteSelectionResult,
        prefix_entries: PrefixEntries,
        next_hops: set[NextHop],
        shortest_metric: int,
        ucmp_weight: Optional[int],
    ) -> Optional[RibUnicastEntry]:
        if not next_hops:
            return None

        # min-nexthop requirement: max over selected announcers' thresholds
        min_next_hop = None
        for node_area in selection.all_node_areas:
            entry = prefix_entries[node_area]
            if entry.min_nexthop is not None and (
                min_next_hop is None or entry.min_nexthop > min_next_hop
            ):
                min_next_hop = entry.min_nexthop
        if min_next_hop is not None and min_next_hop > len(next_hops):
            return None

        # self-advertised anycast: add static next hops of our prepend label
        if selection.has_node(my_node_name):
            prepend_label = None
            for (node, _), entry in prefix_entries.items():
                if node == my_node_name and entry.prepend_label is not None:
                    prepend_label = entry.prepend_label
                    break
            if prepend_label is not None:
                static = self.static_mpls_routes.get(prepend_label)
                if static is not None:
                    for nh in static.nexthops:
                        next_hops.add(
                            NextHop(address=nh.address, metric=0)
                        )

        best_entry = prefix_entries[selection.best_node_area]
        return RibUnicastEntry(
            prefix=prefix,
            nexthops=frozenset(next_hops),
            best_prefix_entry=best_entry,
            best_node_area=selection.best_node_area,
            igp_cost=shortest_metric,
            ucmp_weight=ucmp_weight,
        )

    # -- UCMP (ref SpfSolver.cpp:1092-1162) --------------------------------

    def _get_node_ucmp_result(
        self,
        my_node_name: str,
        fwd_algo: PrefixForwardingAlgorithm,
        area: str,
        link_state: LinkState,
        prefix_entries: PrefixEntries,
        best_keys: set,
        best_metric: float,
    ) -> Optional[NodeUcmpResult]:
        if not self.enable_ucmp:
            return None
        if fwd_algo not in (
            PrefixForwardingAlgorithm.SP_UCMP_ADJ_WEIGHT_PROPAGATION,
            PrefixForwardingAlgorithm.SP_UCMP_PREFIX_WEIGHT_PROPAGATION,
        ):
            return None
        spf = link_state.get_spf_result(my_node_name)
        dst_weights: dict[str, int] = {}
        for dst_node, dst_area in best_keys:
            if dst_area != area:
                continue
            node = spf.get(dst_node)
            if node is None or node.metric != best_metric:
                continue
            entry = prefix_entries[(dst_node, dst_area)]
            if not entry.weight:
                return None  # a best route without weight disables UCMP
            dst_weights[dst_node] = entry.weight
        use_prefix_weight = (
            fwd_algo
            == PrefixForwardingAlgorithm.SP_UCMP_PREFIX_WEIGHT_PROPAGATION
        )
        if self.ucmp_resolver is not None:
            res = self.ucmp_resolver(
                my_node_name, area, link_state, dst_weights,
                use_prefix_weight,
            )
            if res is not NotImplemented:
                return res
        results = link_state.resolve_ucmp_weights(
            spf, dst_weights, use_prefix_weight=use_prefix_weight
        )
        return results.get(my_node_name)

    # -- MPLS label routes (ref SpfSolver.cpp:501-638) ---------------------

    def _node_label_routes(
        self, my_node_name: str, area_link_states: dict[str, LinkState]
    ) -> dict[int, RibMplsEntry]:
        label_to_node: dict[int, tuple[str, RibMplsEntry]] = {}
        for area, link_state in area_link_states.items():
            for node, adj_db in link_state.get_adjacency_databases().items():
                top_label = adj_db.node_label
                if top_label == 0 or not is_mpls_label_valid(top_label):
                    continue
                prior = label_to_node.get(top_label)
                if prior is not None and prior[0] < node:
                    continue  # label conflict: respect smaller node name
                if node == my_node_name:
                    label_to_node[top_label] = (
                        my_node_name,
                        RibMplsEntry(
                            top_label,
                            frozenset(
                                {
                                    NextHop(
                                        address="::",
                                        area=area,
                                        mpls_action=MplsAction(
                                            MplsActionCode.POP_AND_LOOKUP
                                        ),
                                    )
                                }
                            ),
                        ),
                    )
                    continue
                min_metric, nh_nodes = self.get_next_hops_with_metric(
                    my_node_name, {(node, area)}, False, link_state
                )
                if not nh_nodes:
                    continue
                nhs = self.get_next_hops(
                    my_node_name,
                    {(node, area)},
                    False,
                    False,
                    min_metric,
                    nh_nodes,
                    top_label,
                    area,
                    link_state,
                )
                label_to_node[top_label] = (node, RibMplsEntry(top_label, frozenset(nhs)))
        return {label: entry for label, (_, entry) in label_to_node.items()}

    def _adj_label_routes(
        self, my_node_name: str, area_link_states: dict[str, LinkState]
    ) -> list[RibMplsEntry]:
        out = []
        for _, link_state in area_link_states.items():
            for link in link_state.links_from_node(my_node_name):
                top_label = link.adj_label_from_node(my_node_name)
                if top_label == 0 or not is_mpls_label_valid(top_label):
                    continue
                out.append(
                    RibMplsEntry(
                        top_label,
                        frozenset(
                            {
                                NextHop(
                                    address=link.nh_v6_from_node(my_node_name),
                                    if_name=link.iface_from_node(my_node_name),
                                    metric=link.metric_from_node(my_node_name),
                                    mpls_action=MplsAction(MplsActionCode.PHP),
                                    area=link.area,
                                    neighbor_node_name=link.other_node(my_node_name),
                                )
                            }
                        ),
                    )
                )
        return out
