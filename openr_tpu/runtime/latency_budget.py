"""Per-epoch latency budget ledger: gapless churn-to-ack attribution.

Every convergence epoch (KvStore receive -> FIB ack) is decomposed into a
fixed, exhaustive taxonomy of components.  The ledger enforces a
*conservation invariant*: the attributed components plus the residual
``budget.unattributed_ms`` always sum to the measured end-to-end wall time
of the epoch.  A growing residual means the taxonomy rotted (a new stage
appeared that nobody stamps) and pages via its own drift SLO before the
per-component numbers start to mislead.

Mechanics
---------
An :class:`EpochBudget` is a cursor walking the epoch's wall clock: each
``advance(component)`` call attributes the segment ``[cursor, now]`` to
that component and moves the cursor.  ``advance_split`` carves a segment
into sub-components using externally measured durations (e.g. the solver's
``last_timing`` exec/materialize split), clipping so no split can claim
more wall time than the segment actually spans — over-claims fall back to
the primary component, never double-count.

Budgets are keyed by the convergence trace that rides the epoch through
the queues (see ``runtime/tracing.py``), so the decision and FIB actors
can stamp the same epoch without passing a handle around.  Closing a
budget records ``budget.<component>_ms`` stats (windowed p50/p95/p99 via
the counter fabric, exported through OpenMetrics automatically),
``budget.e2e_ms`` and ``budget.unattributed_ms``, and appends the row to
a bounded ring for ``breeze decision budget`` / flight-recorder annexes.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Tuple

from openr_tpu.runtime.counters import counters

#: Canonical, exhaustive taxonomy.  ``tools/lint/metric_names.py`` expands
#: ``budget.<component>_ms`` against this list; adding a component here is
#: the single place the schema changes.
BUDGET_COMPONENTS: Tuple[str, ...] = (
    "ingest_wait",     # KvStore recv -> dispatch-fiber pickup
    "coalesce_hold",   # deliberate coalescing sleep + merge window
    "fence_hold",      # waiting behind the stream fence / requeue hold
    "host_sync",       # LSDB delta read + host->device upload (dispatch)
    "dispatch_gap",    # solve enqueued -> device work actually starts
    "device_exec",     # device kernel execution
    "collect_block",   # host blocked collecting device results
    "payload_apply",   # changed rows -> RouteDatabase/RouteColumnBatch + fib diff
    "program",         # netlink / dataplane programming
    "ack_rtt",         # programming done -> ack observed/published
)

#: Conservation tolerance.  Components are cursor-derived so the sum is
#: exact up to float noise; anything above this is real unattributed time.
CONSERVATION_EPSILON_MS = 0.05

_MAX_ACTIVE = 256
_RING_LEN = 128


class EpochBudget:
    """One epoch's budget: a monotonic cursor over wall time."""

    __slots__ = ("key", "start", "cursor", "components", "meta", "closed")

    def __init__(self, key: Any, start: float, meta: Optional[dict] = None):
        self.key = key
        self.start = float(start)
        self.cursor = float(start)
        self.components: Dict[str, float] = {}
        self.meta = dict(meta or {})
        self.closed = False

    def advance(self, component: str, now: Optional[float] = None) -> float:
        """Attribute ``[cursor, now]`` to *component*; move the cursor.

        Returns the milliseconds attributed.  Clamped non-negative: a
        stale ``now`` (earlier than the cursor) attributes nothing rather
        than going negative and breaking conservation.
        """
        if now is None:
            now = time.monotonic()
        if now < self.cursor:
            now = self.cursor
        dt_ms = (now - self.cursor) * 1e3
        self.cursor = now
        if dt_ms > 0.0:
            self.components[component] = (
                self.components.get(component, 0.0) + dt_ms
            )
        return dt_ms

    def advance_split(
        self,
        splits: Dict[str, Optional[float]],
        primary: str,
        now: Optional[float] = None,
    ) -> float:
        """Carve the segment ``[cursor, now]`` into *splits* (ms values
        measured externally, e.g. solver ``last_timing``), attributing any
        remainder — and any over-claim — to *primary*.

        Each split is clipped to what is left of the segment, in dict
        order, so the sum of attributed parts equals the segment exactly:
        conservation survives noisy external measurements.
        """
        if now is None:
            now = time.monotonic()
        if now < self.cursor:
            now = self.cursor
        seg_ms = (now - self.cursor) * 1e3
        self.cursor = now
        remaining = seg_ms
        for comp, val in splits.items():
            take = min(max(float(val or 0.0), 0.0), remaining)
            if take > 0.0:
                self.components[comp] = self.components.get(comp, 0.0) + take
                remaining -= take
        if remaining > 0.0:
            self.components[primary] = (
                self.components.get(primary, 0.0) + remaining
            )
        return seg_ms

    def top_component(self) -> Tuple[str, float]:
        if not self.components:
            return "", 0.0
        comp = max(self.components, key=self.components.get)
        return comp, self.components[comp]


class LatencyBudgetLedger:
    """Process-global registry of in-flight and recently closed budgets."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active: Dict[Any, EpochBudget] = {}
        self._closed: deque = deque(maxlen=_RING_LEN)
        self.enabled = True

    # -- lifecycle ----------------------------------------------------

    def begin(
        self, key: Any, start: Optional[float] = None, **meta
    ) -> Optional[EpochBudget]:
        if not self.enabled or key is None:
            return None
        if start is None:
            start = time.monotonic()
        bud = EpochBudget(key, start, meta)
        with self._lock:
            existing = self._active.get(key)
            if existing is not None:
                return existing
            while len(self._active) >= _MAX_ACTIVE:
                # Evict the oldest in-flight budget (leaked epoch): its
                # trace died without closing.  Count it — silent eviction
                # would read as perfect conservation.
                oldest = next(iter(self._active))
                del self._active[oldest]
                counters.increment("budget.evicted")
            self._active[key] = bud
        return bud

    def begin_for_trace(self, ctx, **meta) -> Optional[EpochBudget]:
        """Begin a budget keyed by a convergence trace context, anchored
        at the trace's monotonic start so ``ingest_wait`` is real."""
        if ctx is None or not self.enabled:
            return None
        from openr_tpu.runtime.tracing import tracer

        started = tracer.trace_start(ctx)
        return self.begin(("trace", ctx.trace_id), start=started, **meta)

    def of(self, key: Any) -> Optional[EpochBudget]:
        if key is None:
            return None
        with self._lock:
            return self._active.get(key)

    def of_trace(self, ctx) -> Optional[EpochBudget]:
        if ctx is None:
            return None
        return self.of(("trace", ctx.trace_id))

    def discard(self, key: Any) -> None:
        """Drop a budget without recording stats (epoch did not complete
        as a churn-to-ack interval: no-change, not-in-lsdb, coalesced)."""
        if key is None:
            return
        with self._lock:
            if self._active.pop(key, None) is not None:
                counters.increment("budget.discarded")

    def discard_trace(self, ctx) -> None:
        if ctx is not None:
            self.discard(("trace", ctx.trace_id))

    def close(
        self,
        budget: Optional[EpochBudget],
        status: str = "ok",
        final_component: Optional[str] = None,
        now: Optional[float] = None,
    ) -> Optional[dict]:
        """Close a budget: enforce conservation, record stats, ring it.

        ``final_component`` absorbs the tail ``[cursor, now]`` (normally
        ``ack_rtt``).  Returns the closed row (also appended to the ring)
        or None if the budget was absent/already closed.
        """
        if budget is None or budget.closed:
            return None
        budget.closed = True
        with self._lock:
            self._active.pop(budget.key, None)
        if now is None:
            now = time.monotonic()
        if now < budget.cursor:
            now = budget.cursor
        if final_component:
            budget.advance(final_component, now)
        e2e_ms = (now - budget.start) * 1e3
        attributed = sum(budget.components.values())
        unattributed = e2e_ms - attributed
        if unattributed < CONSERVATION_EPSILON_MS:
            unattributed = max(unattributed, 0.0)
        for comp in BUDGET_COMPONENTS:
            counters.add_stat_value(
                f"budget.{comp}_ms", budget.components.get(comp, 0.0)
            )
        counters.add_stat_value("budget.e2e_ms", e2e_ms)
        counters.add_stat_value("budget.unattributed_ms", unattributed)
        if e2e_ms > 0.0:
            counters.set_counter(
                "budget.unattributed_pct",
                int(round(100.0 * unattributed / e2e_ms)),
            )
        counters.increment("budget.epochs")
        if status == "requeued":
            counters.increment("budget.requeued_epochs")
        top_comp, top_ms = budget.top_component()
        row = {
            "key": str(budget.key),
            "status": status,
            "e2e_ms": round(e2e_ms, 3),
            "unattributed_ms": round(unattributed, 3),
            "components": {
                k: round(v, 3) for k, v in budget.components.items()
            },
            "top_component": top_comp,
            "top_ms": round(top_ms, 3),
            "ts_ms": int(time.time() * 1e3),
        }
        if budget.meta:
            row["meta"] = dict(budget.meta)
        with self._lock:
            self._closed.append(row)
        return row

    def close_trace(
        self,
        ctx,
        status: str = "ok",
        final_component: Optional[str] = None,
    ) -> Optional[dict]:
        if ctx is None:
            return None
        return self.close(
            self.of_trace(ctx), status=status, final_component=final_component
        )

    # -- reporting ----------------------------------------------------

    def last_epochs(self, n: int = 16) -> list:
        with self._lock:
            rows = list(self._closed)
        return rows[-n:]

    def report(self) -> dict:
        """Full budget report for ``ctrl.decision.budget``."""
        stats = counters.get_statistics("budget.")
        comps = {}
        for comp in BUDGET_COMPONENTS:
            win = stats.get(f"budget.{comp}_ms")
            if win:
                comps[comp] = win
        rows = self.last_epochs(_RING_LEN)
        ok_rows = [r for r in rows if r["status"] == "ok"] or rows
        per_comp = {c: [] for c in BUDGET_COMPONENTS}
        e2e_samples = []
        for r in ok_rows:
            e2e_samples.append(r["e2e_ms"])
            for c in BUDGET_COMPONENTS:
                per_comp[c].append(r["components"].get(c, 0.0))
        rep = {
            "taxonomy": list(BUDGET_COMPONENTS),
            "components": comps,
            "e2e": stats.get("budget.e2e_ms") or {},
            "unattributed": stats.get("budget.unattributed_ms") or {},
            "conservation": {
                "epsilon_ms": CONSERVATION_EPSILON_MS,
                "epochs": counters.get_counter("budget.epochs"),
                "requeued": counters.get_counter("budget.requeued_epochs"),
                "discarded": counters.get_counter("budget.discarded"),
                "evicted": counters.get_counter("budget.evicted"),
                "unattributed_pct": counters.get_counter(
                    "budget.unattributed_pct"
                ),
            },
            "tail": tail_attribution(per_comp, e2e_samples),
            "last_epochs": rows[-8:],
        }
        return rep

    def snapshot(self) -> dict:
        """Compact annex for flight-recorder bundles."""
        stats = counters.get_statistics("budget.")

        def _q(name):
            win = stats.get(name) or {}
            agg = win.get("600") or (
                next(iter(win.values())) if win else {}
            )
            return {
                k: agg.get(k)
                for k in ("p50", "p95", "p99", "count")
                if agg.get(k) is not None
            }

        return {
            "components": {
                comp: _q(f"budget.{comp}_ms") for comp in BUDGET_COMPONENTS
            },
            "e2e": _q("budget.e2e_ms"),
            "unattributed": _q("budget.unattributed_ms"),
            "epochs": counters.get_counter("budget.epochs"),
            "requeued": counters.get_counter("budget.requeued_epochs"),
            "last_epochs": self.last_epochs(8),
        }

    def clear(self) -> None:
        with self._lock:
            self._active.clear()
            self._closed.clear()


def _pctl(samples: list, q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    idx = min(len(s) - 1, int(round(q * (len(s) - 1))))
    return s[idx]


def tail_attribution(
    component_samples: Dict[str, list], e2e_samples: list
) -> dict:
    """Attribute the p50 -> p99 gap of e2e to components.

    For each component, compute its own p99 - p50 delta; rank descending.
    Reports the top components and the fraction of the e2e gap the top-2
    cover (ISSUE 17 acceptance: >= 0.8 under flapstorm).
    """
    e2e_gap = max(_pctl(e2e_samples, 0.99) - _pctl(e2e_samples, 0.50), 0.0)
    deltas = []
    for comp, samples in component_samples.items():
        d = max(_pctl(samples, 0.99) - _pctl(samples, 0.50), 0.0)
        if d > 0.0:
            deltas.append((comp, d))
    deltas.sort(key=lambda kv: kv[1], reverse=True)
    top2 = sum(d for _, d in deltas[:2])
    return {
        "e2e_gap_ms": round(e2e_gap, 3),
        "ranked": [
            {"component": c, "gap_ms": round(d, 3)} for c, d in deltas[:5]
        ],
        "top2_coverage": (
            round(min(top2 / e2e_gap, 1.0), 3) if e2e_gap > 0.0 else None
        ),
    }


#: Process-global ledger, mirroring ``tracing.tracer`` / counter fabric.
latency_budget = LatencyBudgetLedger()
