"""Trace-purity checker (`host-impurity`, `host-sync`, `traced-loop`).

A function traced by XLA runs ONCE at compile time; Python side effects
inside it silently become trace-time constants (a counter bumps once
per compile, `time.time()` freezes the compile timestamp into the
executable) and host syncs (`.item()`, `float(traced)`) serialize the
async dispatch pipeline. The checker:

1. Collects the traced ROOTS in `ops/` and `decision/tpu_solver.py`:
   functions decorated `@jax.jit`/`@partial(jax.jit, ...)`, and every
   local function handed to `jax.jit`, `vmap`, `pmap`, `lax.scan`,
   `while_loop`, `fori_loop`, `cond`, `switch`, `checkpoint`/`remat`
   (this covers the `bounded_jit_cache`/`instrument_jit` factories:
   the pipeline they compile is always a local `def` passed through
   `jax.jit(...)`).
2. Closes over the same-module and `openr_tpu.ops.*` import call graph
   (a traced function's callees are traced too; nested `def`s inherit
   tracedness).
3. Flags, inside traced code:
   - `host-impurity`: `print`, `time.*`, `counters.*`, logging calls,
     and `np.*` calls outside a static-safe set (dtype constructors,
     `iinfo`/`finfo` — these fold to constants at trace time by
     design; everything else on a traced value is a silent host round
     trip or a trace-time freeze)
   - `host-sync`: `.item()`, `.tolist()`, `.block_until_ready()`,
     `jax.device_get`, and `float()/int()/bool()` on non-trivial
     expressions
   - `traced-loop`: `while` statements (a Python `while` on a traced
     predicate can't trace; on static values it usually wants
     `lax.while_loop` anyway — pragma the intentional static ones)

Static `np.*` on closure constants inside a traced function is
sometimes legitimate (shape math) — those sites take a
`# lint: allow(host-impurity) <reason>` pragma documenting that the
operands are trace-time static.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.lint.core import Finding, Project, SourceFile

CODE_IMPURE = "host-impurity"
CODE_SYNC = "host-sync"
CODE_LOOP = "traced-loop"

# modules whose call graphs we walk (roots + callees live here);
# parallel/ holds the shard_mapped multichip kernels — device code
# like any other, so host impurities there are caught the same way
_TRACED_MODULE_PREFIXES = ("openr_tpu/ops/", "openr_tpu/parallel/")
_TRACED_MODULE_FILES = ("openr_tpu/decision/tpu_solver.py",)

# callables whose function-valued arguments execute under trace
_TRACING_FUNCS = {
    "jit", "vmap", "pmap", "scan", "while_loop", "fori_loop", "cond",
    "switch", "checkpoint", "remat", "custom_jvp", "custom_vjp",
    "shard_map",
}

# np.* attrs that are static-safe inside traced code: dtype
# constructors and dtype-introspection fold to constants at trace time
_ALLOWED_NP = {
    "float32", "float64", "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool_", "dtype",
    "iinfo", "finfo", "ndarray",
}

_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}


def _is_traced_file(rel: str) -> bool:
    return rel in _TRACED_MODULE_FILES or any(
        rel.startswith(p) for p in _TRACED_MODULE_PREFIXES
    )


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class _ModuleGraph:
    """One traced-candidate module: its function defs, the names it
    imports from other traced modules, and its traced-root set."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        # qualname-agnostic: name -> def node (innermost wins is fine;
        # the ops modules don't shadow function names)
        self.defs: dict[str, ast.AST] = {}
        # local alias -> (module rel-ish dotted path, remote name)
        self.imports: dict[str, tuple[str, str]] = {}
        self.traced: set[str] = set()
        self._collect()

    def _collect(self) -> None:
        for node in ast.walk(self.sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs[node.name] = node
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = (
                        node.module, alias.name
                    )
        # roots: decorated with a tracing func, or passed to one
        for name, fn in self.defs.items():
            for dec in fn.decorator_list:
                if self._is_tracing_expr(dec):
                    self.traced.add(name)
        for node in ast.walk(self.sf.tree):
            if not isinstance(node, ast.Call):
                continue
            tname = _terminal_name(node.func)
            if tname == "partial" and node.args:
                tname = _terminal_name(node.args[0])
                func_args = node.args[1:]
            else:
                func_args = node.args
            if tname not in _TRACING_FUNCS:
                continue
            for arg in func_args:
                aname = _terminal_name(arg)
                if aname and aname in self.defs:
                    self.traced.add(aname)

    def _is_tracing_expr(self, dec: ast.AST) -> bool:
        tname = _terminal_name(dec)
        if tname in _TRACING_FUNCS:
            return True
        if isinstance(dec, ast.Call):
            tname = _terminal_name(dec.func)
            if tname in _TRACING_FUNCS:
                return True
            if tname == "partial" and dec.args:
                return _terminal_name(dec.args[0]) in _TRACING_FUNCS
        return False


def _propagate(graphs: dict[str, _ModuleGraph]) -> None:
    """Traced closure: callees of traced functions become traced, both
    same-module and across `openr_tpu.ops.*` imports."""
    # dotted module name -> graph (openr_tpu/ops/spf.py -> openr_tpu.ops.spf)
    by_dotted = {
        g.sf.rel[:-3].replace("/", "."): g for g in graphs.values()
    }
    changed = True
    while changed:
        changed = False
        for g in graphs.values():
            for name in list(g.traced):
                fn = g.defs.get(name)
                if fn is None:
                    continue
                # nested defs inherit tracedness
                for node in ast.walk(fn):
                    if (
                        isinstance(
                            node, (ast.FunctionDef, ast.AsyncFunctionDef)
                        )
                        and node is not fn
                        and node.name not in g.traced
                    ):
                        g.traced.add(node.name)
                        g.defs.setdefault(node.name, node)
                        changed = True
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    cname = _terminal_name(node.func)
                    if cname is None:
                        continue
                    if cname in g.defs and cname not in g.traced:
                        g.traced.add(cname)
                        changed = True
                    imp = g.imports.get(cname)
                    if imp is not None:
                        tgt = by_dotted.get(imp[0])
                        if (
                            tgt is not None
                            and imp[1] in tgt.defs
                            and imp[1] not in tgt.traced
                        ):
                            tgt.traced.add(imp[1])
                            changed = True


def _flag_impurities(g: _ModuleGraph, findings: list[Finding]) -> None:
    sf = g.sf
    for name in sorted(g.traced):
        fn = g.defs.get(name)
        if fn is None:
            continue
        # nested traced defs are also walked on their own pass; the
        # (path, line, code, detail) dedup below collapses the overlap
        for node in ast.walk(fn):
            if isinstance(node, ast.While):
                findings.append(Finding(
                    sf.rel, node.lineno, CODE_LOOP,
                    sf.scope_at(node.lineno), "while",
                    "Python `while` inside traced code — a traced "
                    "predicate can't drive it; use lax.while_loop (or "
                    "pragma if genuinely trace-time static)",
                ))
                continue
            if not isinstance(node, ast.Call):
                continue
            fnode = node.func
            tname = _terminal_name(fnode)
            scope = sf.scope_at(node.lineno)
            if tname == "print":
                findings.append(Finding(
                    sf.rel, node.lineno, CODE_IMPURE, scope, "print",
                    "print() inside traced code runs once at compile "
                    "time, never per solve",
                ))
            elif (
                isinstance(fnode, ast.Attribute)
                and isinstance(fnode.value, ast.Name)
                and fnode.value.id == "time"
            ):
                findings.append(Finding(
                    sf.rel, node.lineno, CODE_IMPURE, scope,
                    f"time.{fnode.attr}",
                    f"time.{fnode.attr}() inside traced code freezes "
                    f"the compile-time clock into the executable",
                ))
            elif (
                isinstance(fnode, ast.Attribute)
                and isinstance(fnode.value, ast.Name)
                and fnode.value.id in ("counters", "log", "logger",
                                       "logging")
            ):
                findings.append(Finding(
                    sf.rel, node.lineno, CODE_IMPURE, scope,
                    f"{fnode.value.id}.{fnode.attr}",
                    f"{fnode.value.id}.{fnode.attr}() inside traced "
                    f"code fires once per compile, not per solve — "
                    f"hoist it to the dispatch wrapper",
                ))
            elif (
                isinstance(fnode, ast.Attribute)
                and isinstance(fnode.value, ast.Name)
                and fnode.value.id in ("np", "numpy")
                and fnode.attr not in _ALLOWED_NP
            ):
                findings.append(Finding(
                    sf.rel, node.lineno, CODE_IMPURE, scope,
                    f"np.{fnode.attr}",
                    f"np.{fnode.attr}() inside traced code — on a "
                    f"traced value this is a silent host round trip; "
                    f"use jnp, or pragma if the operands are "
                    f"trace-time static",
                ))
            elif (
                isinstance(fnode, ast.Attribute)
                and fnode.attr in _SYNC_ATTRS
                and not node.args
            ):
                findings.append(Finding(
                    sf.rel, node.lineno, CODE_SYNC, scope,
                    f".{fnode.attr}()",
                    f".{fnode.attr}() inside traced code forces a "
                    f"device sync at trace time",
                ))
            elif (
                isinstance(fnode, ast.Attribute)
                and fnode.attr == "device_get"
            ):
                findings.append(Finding(
                    sf.rel, node.lineno, CODE_SYNC, scope, "device_get",
                    "jax.device_get inside traced code blocks the "
                    "dispatch pipeline",
                ))
            elif (
                tname in ("float", "int", "bool")
                and isinstance(fnode, ast.Name)
                and len(node.args) == 1
                and isinstance(node.args[0], (ast.Subscript, ast.Call))
            ):
                findings.append(Finding(
                    sf.rel, node.lineno, CODE_SYNC, scope, f"{tname}()",
                    f"{tname}() on an indexed/computed value inside "
                    f"traced code is a host sync on a traced array",
                ))


def run(project: Project) -> list[Finding]:
    graphs = {
        sf.rel: _ModuleGraph(sf)
        for sf in project.files
        if _is_traced_file(sf.rel)
    }
    _propagate(graphs)
    findings: list[Finding] = []
    for g in graphs.values():
        _flag_impurities(g, findings)
    # a line flagged once is enough even if two traced parents reach it
    seen: set[tuple] = set()
    out = []
    for fd in findings:
        k = (fd.path, fd.line, fd.code, fd.detail)
        if k not in seen:
            seen.add(k)
            out.append(fd)
    return out
