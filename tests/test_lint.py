"""tools.lint suite + runtime affinity sentinel tests.

Fixture-based coverage for the eight AST checkers (seeded violations
must be flagged, clean idioms must not), the pragma/allowlist
suppression machinery, a repo-runs-clean regression guard, and the
thread-ownership sentinel — including the chaos-lane drill that proves
a deliberate cross-thread `TpuSpfSolver` dispatch trips it.
"""

import json
import subprocess
import sys
import textwrap
import threading

import pytest

from openr_tpu.runtime import affinity
from openr_tpu.runtime.counters import counters
from tools.lint import affinity as affinity_check
from tools.lint import blocking as blocking_check
from tools.lint import donation as donation_check
from tools.lint import excepts as excepts_check
from tools.lint import metric_names as metric_check
from tools.lint import purity as purity_check
from tools.lint import recompile as recompile_check
from tools.lint import shardcheck as shard_check
from tools.lint.core import (
    REPO_ROOT,
    Allowlist,
    Project,
    apply_suppressions,
)


def make_project(tmp_path, files, packages=("pkg",)):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return Project(tmp_path, list(packages))


def codes(findings):
    return {f.code for f in findings}


# -- exception hygiene -----------------------------------------------------

EXCEPTS_FIXTURE = """\
    from openr_tpu.runtime.counters import counters

    def swallows():
        try:
            work()
        except Exception:
            pass  # seeded violation

    def counted():
        try:
            work()
        except Exception:
            counters.increment("pkg.errors")

    def reraises():
        try:
            work()
        except Exception:
            raise

    def narrow():
        try:
            work()
        except ValueError:
            pass

    def annotated():
        try:
            work()
        # lint: allow(broad-except) fixture: intentionally swallowed
        except Exception:
            pass
"""


def test_excepts_flags_swallow_and_honors_compliance(tmp_path):
    project = make_project(tmp_path, {"pkg/mod.py": EXCEPTS_FIXTURE})
    findings = excepts_check.run(project)
    assert [f.scope for f in findings] == ["swallows", "annotated"]
    allow = Allowlist.load(tmp_path / "missing.json")
    remaining = apply_suppressions(findings, project, allow)
    assert [f.scope for f in remaining] == ["swallows"]
    assert remaining[0].code == "broad-except"


def test_bare_pragma_is_itself_a_finding(tmp_path):
    project = make_project(tmp_path, {
        "pkg/mod.py": """\
            def f():
                try:
                    work()
                # lint: allow(broad-except)
                except Exception:
                    pass
        """,
    })
    sf = project.files[0]
    assert [f.code for f in sf.pragma_errors] == ["bare-pragma"]
    # a reason-less pragma suppresses nothing
    assert codes(excepts_check.run(project)) == {"broad-except"}


# -- blocking-in-fiber -----------------------------------------------------

BLOCKING_FIXTURE = """\
    import time

    async def fiber(self, fut, sock):
        time.sleep(1)                       # seeded violation
        fut.result()                        # seeded violation
        sock.recv(65536)                    # seeded violation
        self.solver.collect_route_db(p)     # seeded violation
        await self.connect()                # awaited coroutine: fine
        fut.result(timeout=0)               # bounded wait: not flagged

    def host_side(fut):
        time.sleep(1)      # sync context: fine
        return fut.result()
"""


def test_blocking_flags_only_async_bodies(tmp_path):
    project = make_project(tmp_path, {"pkg/mod.py": BLOCKING_FIXTURE})
    findings = blocking_check.run(project)
    assert all(f.code == "blocking-call" for f in findings)
    assert {f.detail for f in findings} == {
        "time.sleep", "result()", "recv", "collect_route_db",
    }
    assert all(f.scope == "fiber" for f in findings)


# -- actor affinity (static) -----------------------------------------------

AFFINITY_FIXTURE = """\
    from openr_tpu.runtime import affinity

    class Actor:
        pass

    class Fib(Actor):
        pass

    def module_level(x):
        return x

    class Decision:
        def __init__(self, fib):
            self.fib = fib

        @affinity.executor_safe
        def collect(self):
            return self._pending

        async def run(self, loop, ex):
            await loop.run_in_executor(ex, self._prepare)   # escape
            await loop.run_in_executor(ex, lambda: self.x)  # escape
            await loop.run_in_executor(ex, self.collect)    # safe
            await loop.run_in_executor(ex, module_level)    # fine

        def submit_closure(self, ex):
            prep = self._dispatch_one()

            def local():
                return self.state

            ex.submit(prep)    # escape: self-derived closure
            ex.submit(local)   # escape: nested def captures locals

        def poke(self):
            self.fib.route_db = {}   # cross-actor write
"""


def test_affinity_static_checker(tmp_path):
    project = make_project(tmp_path, {"pkg/mod.py": AFFINITY_FIXTURE})
    assert project.actor_classes >= {"Actor", "Fib"}
    assert "collect" in project.executor_safe_names
    findings = affinity_check.run(project)
    escapes = [f for f in findings if f.code == "executor-escape"]
    xwrites = [f for f in findings if f.code == "cross-actor-write"]
    assert {f.detail for f in escapes} == {
        "self._prepare", "<lambda>", "prep", "local",
    }
    assert len(xwrites) == 1 and xwrites[0].scope == "Decision.poke"


# -- trace purity ----------------------------------------------------------

PURITY_FIXTURE = """\
    import numpy as np
    import jax
    import jax.numpy as jnp

    @jax.jit
    def traced(x):
        print(x)                      # seeded host-impurity
        while x.shape[0]:             # seeded traced-loop
            break
        return helper(x)

    def helper(x):
        return np.asarray(x)          # impure, reached from traced root

    def host_only(x):
        print(x)                      # untraced: fine
        return x.item()
"""


def test_purity_walks_call_graph_from_jit_roots(tmp_path):
    project = make_project(
        tmp_path,
        {"openr_tpu/ops/fixture_mod.py": PURITY_FIXTURE},
        packages=("openr_tpu",),
    )
    findings = purity_check.run(project)
    assert {(f.code, f.scope) for f in findings} == {
        ("host-impurity", "traced"),   # print
        ("traced-loop", "traced"),     # while
        ("host-impurity", "helper"),   # np.asarray via call graph
    }


def test_purity_clean_kernel_is_silent(tmp_path):
    project = make_project(
        tmp_path,
        {
            "openr_tpu/ops/clean_mod.py": """\
                import jax
                import jax.numpy as jnp
                import numpy as np

                @jax.jit
                def kernel(x):
                    return jnp.where(x > 0, x, np.int32(0))
            """,
        },
        packages=("openr_tpu",),
    )
    assert purity_check.run(project) == []


# -- metric names ----------------------------------------------------------

def test_metric_collision_detected(tmp_path):
    project = make_project(tmp_path, {
        "pkg/mod.py": """\
            def f(counters):
                counters.increment("decision.spf.runs")
                counters.increment("decision.spf_runs")
        """,
    })
    findings = metric_check.run(project)
    assert codes(findings) == {"metric-collision"}
    assert "normalize to" in findings[0].message


def test_metric_stat_families_expand(tmp_path):
    # a stat family claims its derived exposition names too
    project = make_project(tmp_path, {
        "pkg/mod.py": """\
            def f(counters):
                counters.add_stat_value("fib.program.ms", 1)
                counters.increment("fib.program.ms_max")
        """,
    })
    assert codes(metric_check.run(project)) == {"metric-collision"}


def test_metric_budget_components_expand(tmp_path):
    # ISSUE 17: the budget ledger emits `budget.<component>_ms` with a
    # runtime component name — the checker expands the placeholder over
    # the canonical taxonomy, so a concrete family colliding with one
    # of the expanded per-component names is caught
    project = make_project(tmp_path, {
        "pkg/mod.py": """\
            def f(counters, comp):
                counters.add_stat_value(f"budget.{comp}_ms", 1)
                counters.increment("budget.host_sync_ms_sum")
        """,
    })
    findings = metric_check.run(project)
    assert codes(findings) == {"metric-collision"}
    assert "budget.host_sync_ms" in findings[0].message


# -- allowlist round-trip --------------------------------------------------

def test_allowlist_round_trip_and_unused(tmp_path):
    project = make_project(tmp_path, {
        "pkg/mod.py": """\
            def swallows():
                try:
                    work()
                except Exception:
                    pass
        """,
    })
    (finding,) = excepts_check.run(project)
    al_path = tmp_path / "allowlist.json"
    al_path.write_text(json.dumps({"entries": [
        {"key": finding.key, "reason": "fixture: blessed"},
        {"key": "pkg/gone.py::f::broad-except::", "reason": "stale"},
    ]}))
    allow = Allowlist.load(al_path)
    assert not allow.errors
    assert apply_suppressions([finding], project, allow) == []
    # the matched key is consumed; the stale one surfaces as unused
    assert allow.unused() == ["pkg/gone.py::f::broad-except::"]


def test_allowlist_requires_reason(tmp_path):
    al_path = tmp_path / "allowlist.json"
    al_path.write_text(json.dumps({"entries": [{"key": "a::b::c::d"}]}))
    allow = Allowlist.load(al_path)
    assert allow.errors and "reason" in allow.errors[0]
    assert allow.entries == {}


def test_allowlist_keys_are_line_number_free(tmp_path):
    # inserting lines above the finding must not invalidate its key
    src = """\
        def swallows():
            try:
                work()
            except Exception:
                pass
    """
    p1 = make_project(tmp_path / "a", {"pkg/mod.py": src})
    p2 = make_project(tmp_path / "b", {"pkg/mod.py": "import os\n\n\n" + textwrap.dedent(src)})
    (f1,) = excepts_check.run(p1)
    (f2,) = excepts_check.run(p2)
    assert f1.line != f2.line
    assert f1.key == f2.key


def test_purity_traces_relax_kernel_roots():
    """The shared round-loop module (ops/relax.py) is device code:
    every loop body it hands to while_loop/fori_loop must be
    discovered as a traced root by the purity walker (regression
    guard: the ops/ module prefix covers the kernel extraction), and
    the shipped kernels must run clean."""
    project = Project(REPO_ROOT, ["openr_tpu"])
    sf = project.file("openr_tpu/ops/relax.py")
    assert sf is not None
    assert purity_check._is_traced_file(sf.rel)
    g = purity_check._ModuleGraph(sf)
    # make_relax's fori body, run_sync's trip loop, run_bucketed's
    # ladder pass + rung loop + epoch loop all ride lax control flow
    assert {
        "cls", "body", "cond", "one", "lbody", "lcond", "ebody", "econd",
    } <= g.traced
    assert not [
        f for f in purity_check.run(project)
        if f.path == "openr_tpu/ops/relax.py"
    ]


# -- recompile hygiene -----------------------------------------------------

RECOMPILE_FIXTURE = """\
    import functools

    import jax
    import jax.numpy as jnp

    _tuning = {"unroll": 4}       # mutable module global
    UNROLL = 4                    # ALL_CAPS constant: trace-safe

    def factory(n_cap, wide):
        scale = 2 if wide else 1

        def pipeline(x):
            k = _tuning["unroll"]         # seeded trace-capture
            return jnp.sum(x) * k * UNROLL * n_cap * scale

        return jax.jit(pipeline)

    @functools.lru_cache(maxsize=8)
    def cached_factory(n_cap):            # seeded unbounded-jit-cache
        def pipeline(x):
            return x * n_cap

        return jax.jit(pipeline)
"""


def test_recompile_flags_captures_and_unbounded_cache(tmp_path):
    project = make_project(
        tmp_path,
        {"openr_tpu/ops/fix_recompile.py": RECOMPILE_FIXTURE},
        packages=("openr_tpu",),
    )
    findings = recompile_check.run(project)
    assert {(f.code, f.detail) for f in findings} == {
        ("trace-capture", "_tuning"),
        ("unbounded-jit-cache", "cached_factory"),
    }
    # the capture finding names the mutable-global hazard, not a
    # generic unresolved symbol
    cap = next(f for f in findings if f.code == "trace-capture")
    assert "mutable module global" in cap.message


def test_recompile_clean_factory_is_silent(tmp_path):
    # everything the traced closure reads flows through the factory
    # parameters/locals, imports, or ALL_CAPS constants — the capacity
    # signature owns it all
    project = make_project(
        tmp_path,
        {
            "openr_tpu/ops/fix_recompile_ok.py": """\
                import jax
                import jax.numpy as jnp

                UNROLL = 4

                def factory(n_cap, wide):
                    scale = 2 if wide else 1

                    def pipeline(x):
                        return jnp.sum(x) * n_cap * scale * UNROLL

                    return jax.jit(pipeline)
            """,
        },
        packages=("openr_tpu",),
    )
    assert recompile_check.run(project) == []


# -- sharding contracts ----------------------------------------------------

# the PR 13 bug-shape, seeded: a mesh-aware jitted pull pipeline whose
# concatenated boundary buffer is never re-pinned, plus the
# traced-shift roll that GSPMD miscompiles to an unreduced partial-sum
SHARD_FIXTURE = """\
    import jax
    import jax.numpy as jnp

    def make_pull(mesh, rep):
        def pull(a, b, shift):
            delta_buf = jnp.concatenate([a, b])       # never constrained
            rolled = jnp.roll(delta_buf, shift, axis=1)
            return rolled
        return jax.jit(pull)

    def naked(x):
        def body(v):
            return jax.lax.pmin(v, "rows")
        return jax.jit(body)(x)
"""


def test_shardcheck_catches_pr13_regression_shape(tmp_path):
    project = make_project(
        tmp_path,
        {"openr_tpu/parallel/fix_shard.py": SHARD_FIXTURE},
        packages=("openr_tpu",),
    )
    findings = shard_check.run(project)
    got = {(f.code, f.detail) for f in findings}
    assert ("unconstrained-boundary", "delta_buf") in got
    assert ("sharded-axis-roll", "roll") in got
    assert ("naked-collective", "pmin") in got
    assert ("undeclared-axis", "pmin:rows") in got
    roll = next(f for f in findings if f.code == "sharded-axis-roll")
    assert "partial-sum" in roll.message


def test_shardcheck_clean_shard_map_module_is_silent(tmp_path):
    # the production shape: collectives under shard_map against a
    # declared axis; the boundary buffer re-pinned (on the mesh path
    # only — path-insensitive on purpose)
    project = make_project(
        tmp_path,
        {
            "openr_tpu/parallel/fix_shard_ok.py": """\
                import jax
                import jax.numpy as jnp
                from jax.sharding import Mesh, PartitionSpec as P

                def make_pull(mesh, rep):
                    def pull(a, b):
                        delta_buf = jnp.concatenate([a, b])
                        if mesh is not None:
                            delta_buf = jax.lax.with_sharding_constraint(
                                delta_buf, rep)
                        return delta_buf
                    return jax.jit(pull)

                def make_mc(mesh):
                    def local_fn(x):
                        i = jax.lax.axis_index("graph")
                        return jax.lax.pmin(x + i, "graph")
                    from jax.experimental.shard_map import shard_map
                    return shard_map(
                        local_fn, mesh=mesh,
                        in_specs=(P("graph"),), out_specs=P("graph"),
                    )
            """,
        },
        packages=("openr_tpu",),
    )
    assert shard_check.run(project) == []


def test_shardcheck_repo_declares_its_axes():
    # the production multichip module passes its own contract: both
    # mesh axes are declared, every collective sits under shard_map
    project = Project(REPO_ROOT, ["openr_tpu"])
    sf = project.file("openr_tpu/parallel/sharding.py")
    assert shard_check._declared_axes(sf) >= {"batch", "graph"}
    assert not [
        f for f in shard_check.run(project)
        if f.path == "openr_tpu/parallel/sharding.py"
    ]


# -- buffer donation -------------------------------------------------------

DONATION_FIXTURE = """\
    import jax

    def _scatter_jit(donate=False):
        def scatter(arr, idx, vals):
            return arr.at[idx].set(vals)
        if donate:
            return jax.jit(scatter, donate_argnums=(0,))
        return jax.jit(scatter)

    class Solver:
        def _scatter_counted(self, d_arr, idx, vals):
            return _scatter_jit(True)(d_arr, idx, vals)

        def bad(self, ad, idx, vals):
            stale = self._scatter_counted(ad.d_w, idx, vals)
            return stale, ad.d_w.shape       # seeded donated-read

        def good(self, ad, idx, vals):
            ad.d_w = self._scatter_counted(ad.d_w, idx, vals)
            return ad.d_w.shape              # rebind idiom: fine
"""


def test_donation_flags_read_after_donate_through_wrappers(tmp_path):
    project = make_project(
        tmp_path,
        {"openr_tpu/ops/fix_donation.py": DONATION_FIXTURE},
        packages=("openr_tpu",),
    )
    findings = donation_check.run(project)
    assert [(f.code, f.detail, f.scope) for f in findings] == [
        ("donated-read", "ad.d_w", "Solver.bad"),
    ]


def test_donation_kwargs_dict_form_indexes_as_donating(tmp_path):
    # _mc_scatter_jit's `{"donate_argnums": (0,)} if donate else {}`
    # shape must index the factory as donating
    project = make_project(
        tmp_path,
        {
            "openr_tpu/ops/fix_donation_kw.py": """\
                import jax

                def _mc_scatter_jit(sharding, donate=False):
                    def scatter(arr, idx, vals):
                        return arr.at[idx].set(vals)
                    kw = {"donate_argnums": (0,)} if donate else {}
                    return jax.jit(scatter, **kw)

                def syncs(buf, idx, vals, sh):
                    out = _mc_scatter_jit(sh, True)(buf, idx, vals)
                    return out + buf          # seeded donated-read
            """,
        },
        packages=("openr_tpu",),
    )
    findings = donation_check.run(project)
    assert [(f.code, f.detail) for f in findings] == [
        ("donated-read", "buf"),
    ]


# -- pragma placement on decorated defs ------------------------------------

def test_pragma_above_decorator_stack_covers_the_def(tmp_path):
    project = make_project(
        tmp_path,
        {
            "openr_tpu/ops/fix_decorated.py": """\
                import functools

                import jax

                # lint: allow(unbounded-jit-cache) fixture: blessed cache
                @functools.lru_cache(maxsize=2)
                @functools.wraps(print)
                def cached(n):
                    return jax.jit(lambda x: x * n)
            """,
        },
        packages=("openr_tpu",),
    )
    findings = recompile_check.run(project)
    assert codes(findings) == {"unbounded-jit-cache"}
    # the finding anchors at the `def` line, below the whole decorator
    # stack — the pragma above the first decorator must still cover it
    allow = Allowlist.load(tmp_path / "missing.json")
    assert apply_suppressions(findings, project, allow) == []


# -- CLI: stale allowlist fails, --files narrows the report ----------------

def test_unused_allowlist_entry_fails_full_run(tmp_path, capsys):
    from tools.lint.__main__ import main as lint_main

    al = tmp_path / "allowlist.json"
    al.write_text(json.dumps({"entries": [
        {"key": "openr_tpu/gone.py::f::broad-except::x",
         "reason": "stale fixture entry"},
    ]}))
    rc = lint_main(["--allowlist", str(al)])
    err = capsys.readouterr().err
    assert rc == 1
    assert "unused allowlist entry" in err
    assert "openr_tpu/gone.py::f::broad-except::x" in err


def test_files_lane_narrows_report_and_skips_staleness(tmp_path, capsys):
    # the diff-aware PR lane: a stale allowlist entry must NOT fail a
    # partial report (it can't prove staleness), and findings outside
    # the named files are filtered from the report
    from tools.lint.__main__ import main as lint_main

    al = tmp_path / "allowlist.json"
    al.write_text(json.dumps({"entries": [
        {"key": "openr_tpu/gone.py::f::broad-except::x",
         "reason": "stale fixture entry"},
    ]}))
    rc = lint_main([
        "--allowlist", str(al),
        "--files", "openr_tpu/ops/relax.py",
    ])
    out = capsys.readouterr()
    assert rc == 0, out.err
    assert "unused allowlist entry" not in out.err


# -- the repo itself runs clean --------------------------------------------

def test_repo_lint_is_clean():
    """Regression guard: the shipped tree has zero unallowlisted
    findings (the CI gate this suite exists for)."""
    res = subprocess.run(
        [sys.executable, "-m", "tools.lint"],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "OK" in res.stdout


# -- runtime sentinel ------------------------------------------------------

@pytest.fixture
def affinity_on():
    prev = affinity.enabled()
    affinity.set_enabled(True)
    yield
    affinity.set_enabled(prev)


class Box:
    pass


def _violations():
    return counters.get_counter("runtime.affinity.violations") or 0


def test_sentinel_disabled_is_inert():
    prev = affinity.enabled()
    affinity.set_enabled(False)
    try:
        obj = Box()
        affinity.bind_owner(obj, "box")
        assert "_affinity_ident" not in obj.__dict__
        done = []
        t = threading.Thread(
            target=lambda: done.append(affinity.assert_owner(obj))
        )
        t.start()
        t.join(timeout=10)
        assert done == [None]  # no binding, no raise, no counter
    finally:
        affinity.set_enabled(prev)


def test_sentinel_first_touch_binds_then_enforces(affinity_on):
    obj = Box()
    affinity.assert_owner(obj, "write")  # first touch claims ownership
    assert obj.__dict__["_affinity_ident"] == threading.get_ident()
    affinity.assert_owner(obj, "write")  # same thread: fine
    before = _violations()
    caught = []

    def rogue():
        try:
            affinity.assert_owner(obj, "rogue_write")
        except affinity.AffinityViolation as e:
            caught.append(e)

    t = threading.Thread(target=rogue, name="rogue")
    t.start()
    t.join(timeout=10)
    assert len(caught) == 1
    assert "rogue_write" in str(caught[0])
    assert "dispatch-collect" in str(caught[0])
    assert _violations() == before + 1


def test_sentinel_rebind_transfers_ownership(affinity_on):
    obj = Box()
    holder = []

    def bind_elsewhere():
        affinity.bind_owner(obj, "box")
        holder.append(obj.__dict__["_affinity_ident"])

    t = threading.Thread(target=bind_elsewhere)
    t.start()
    t.join(timeout=10)
    assert holder and holder[0] != threading.get_ident()
    # supervised-restart pattern: the new owner re-claims explicitly
    affinity.bind_owner(obj, "box")
    affinity.assert_owner(obj, "write")  # no raise


def test_actor_add_task_guarded(affinity_on):
    from tests.conftest import run_async
    from openr_tpu.runtime.actor import Actor

    @run_async
    async def drive():
        a = Actor("guinea")
        await a.start()  # binds the loop thread as owner
        caught = []

        async def noop():
            pass

        def rogue():
            coro = noop()
            try:
                a.add_task(coro, name="rogue")
            except affinity.AffinityViolation as e:
                caught.append(e)
                coro.close()

        t = threading.Thread(target=rogue, name="rogue")
        t.start()
        t.join(timeout=10)
        await a.stop()
        return caught

    caught = drive()
    assert len(caught) == 1
    assert "add_task" in str(caught[0])


# -- chaos drill: cross-thread solver dispatch -----------------------------

@pytest.mark.chaos
def test_chaos_sentinel_catches_cross_thread_solver_dispatch(affinity_on):
    """The drill the sentinel exists for: a deliberate cross-thread
    touch of `TpuSpfSolver` dispatch state (prev_dist seeding, vantage
    cache, drain journal) must fail loudly instead of corrupting
    routes. The owning thread solves once to bind; a rogue thread then
    re-dispatches and must be rejected."""
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.tpu_solver import TpuSpfSolver
    from tests.test_spf_solver import prefix_db, square_states

    states = square_states()
    ps = PrefixState()
    ps.update_prefix_database(prefix_db("d", "fd00::d/128"))
    solver = TpuSpfSolver("a")
    db = solver.build_route_db("a", states, ps)  # binds this thread
    assert db is not None and "fd00::d/128" in db.unicast_routes

    before = _violations()
    outcome = []

    def rogue():
        try:
            outcome.append(("db", solver.build_route_db("a", states, ps)))
        except affinity.AffinityViolation as e:
            outcome.append(("violation", e))

    t = threading.Thread(target=rogue, name="rogue-solver")
    t.start()
    t.join(timeout=60)
    assert outcome and outcome[0][0] == "violation", (
        "cross-thread dispatch must trip the sentinel, got: "
        f"{outcome!r}"
    )
    assert "dispatch_route_db" in str(outcome[0][1])
    assert _violations() == before + 1

    # the owning thread is unaffected and keeps solving
    db2 = solver.build_route_db("a", states, ps)
    assert db2 is not None


def test_purity_and_donation_trace_stream_epoch_roots():
    """ISSUE 16: the fused streaming-epoch kernel is device code end to
    end. ops/stream.py rides the ops/ traced prefix (its column-diff +
    compaction stages are purity-analyzed), the solver module's
    `pipeline` jit root — which _stream_pipeline wraps for the fused
    epoch — is discovered, and the stream stages' function-local
    imports resolve to the traced module, so a host impurity seeded in
    either stage would flow to the root's findings. The donation
    checker must index the stream executable's conditional kwargs-dict
    donation (the epoch double-buffer's donated planes + warm seed),
    and the shipped modules must run clean."""
    project = Project(REPO_ROOT, ["openr_tpu"])
    sf = project.file("openr_tpu/ops/stream.py")
    assert sf is not None
    assert purity_check._is_traced_file(sf.rel)
    solver = project.file("openr_tpu/decision/tpu_solver.py")
    g = purity_check._ModuleGraph(solver)
    assert "pipeline" in g.traced, g.traced
    assert g.imports.get("column_diff") == (
        "openr_tpu.ops.stream", "column_diff"
    )
    assert g.imports.get("compact_changed_rows") == (
        "openr_tpu.ops.stream", "compact_changed_rows"
    )
    # the streaming executable donates the prev planes + distance seed
    # (positions 9-14) through the conditional dict form — the
    # read-after-donate rule must see every position
    donated = donation_check._factory_donations(
        g.defs["_stream_pipeline"]
    )
    assert {9, 10, 11, 12, 13, 14} <= donated, donated
    findings = [
        f
        for f in purity_check.run(project) + donation_check.run(project)
        if f.path in (
            "openr_tpu/ops/stream.py",
            "openr_tpu/decision/tpu_solver.py",
        )
    ]
    assert not findings, findings
