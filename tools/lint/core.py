"""Shared machinery for the `tools.lint` checkers.

One parsed-AST pass over the package feeds all eight checkers:

  - `SourceFile` — path, text, AST, per-line suppression pragmas, and a
    line -> enclosing-scope (dotted qualname) map.
  - `Project` — the file set plus cross-file indexes the checkers need
    (Actor subclasses, `@executor_safe` names).
  - `Allowlist` — the JSON baseline for findings that are intentional
    but don't warrant an inline pragma. Keys are line-number-free
    (`path::scope::code::detail`) so routine edits don't churn them.

Suppression, in priority order:

  1. inline pragma on the flagged line or the line above:
         # lint: allow(<code>) <reason — mandatory>
     (`# noqa: BLE001 — reason` is also honored for `broad-except`,
     matching ruff's vocabulary for pre-existing annotations)
  2. an allowlist entry in `tools/lint/allowlist.json` with a reason.

Both forms REQUIRE a reason string; a bare pragma is itself a finding.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
DEFAULT_ALLOWLIST = Path(__file__).resolve().parent / "allowlist.json"

# `# lint: allow(code-a, code-b) reason...`
_PRAGMA_RE = re.compile(
    r"#\s*lint:\s*allow\(([A-Za-z0-9_,\- ]+)\)\s*(.*)$"
)
# existing ruff-vocabulary annotations count for broad-except
_NOQA_BLE_RE = re.compile(r"#\s*noqa:\s*BLE001\b\s*[-—–:]*\s*(.*)$")


@dataclass
class Finding:
    path: str  # repo-relative, forward slashes
    line: int
    code: str
    scope: str  # dotted qualname of enclosing def/class, or <module>
    detail: str  # stable short token (callable name, metric name, ...)
    message: str

    @property
    def key(self) -> str:
        return f"{self.path}::{self.scope}::{self.code}::{self.detail}"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: [{self.code}] {self.message}\n"
            f"    scope={self.scope}  allowlist-key={self.key}"
        )


class SourceFile:
    def __init__(self, path: Path, root: Path):
        self.path = path
        self.rel = path.relative_to(root).as_posix()
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=str(path))
        # (qualname, start, end) intervals, innermost match wins —
        # built first: the pragma scan attributes bare-pragma findings
        # to their enclosing scope
        self._scopes: list[tuple[str, int, int]] = []
        self._build_scopes()
        # {code: {line numbers where a pragma suppresses that code}}
        self._pragmas: dict[str, set[int]] = {}
        self.pragma_errors: list[Finding] = []
        self._scan_pragmas()

    # -- pragmas -----------------------------------------------------------

    def _scan_pragmas(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(line)
            if m:
                codes = [c.strip() for c in m.group(1).split(",")]
                reason = m.group(2).strip()
                if not reason:
                    self.pragma_errors.append(Finding(
                        self.rel, i, "bare-pragma", self.scope_at(i), "",
                        "lint pragma without a reason string — say why",
                    ))
                    continue
                for code in codes:
                    if code:
                        # a pragma covers its own line and the next one
                        # (annotation-above style)
                        self._pragmas.setdefault(code, set()).update(
                            (i, i + 1)
                        )
                continue
            m = _NOQA_BLE_RE.search(line)
            if m and m.group(1).strip():
                self._pragmas.setdefault("broad-except", set()).update(
                    (i, i + 1)
                )
        self._extend_over_decorators()

    def _extend_over_decorators(self) -> None:
        """A pragma above a decorated def's FIRST decorator covers the
        `def` line too. Findings anchor at the def's lineno, which for
        a decorated def sits below the whole decorator stack — without
        this, `# lint: allow(...)` placed where a human naturally puts
        it (above the decorators) silently failed to suppress."""
        stacks = [
            (min(d.lineno for d in node.decorator_list), node.lineno)
            for node in ast.walk(self.tree)
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            )
            and node.decorator_list
        ]
        for lines in self._pragmas.values():
            extra = set()
            for dec_start, def_line in stacks:
                if any(dec_start <= c <= def_line for c in lines):
                    extra.update(range(dec_start, def_line + 1))
            lines |= extra

    def suppressed(self, code: str, line: int) -> bool:
        return line in self._pragmas.get(code, ())

    # -- scopes ------------------------------------------------------------

    def _build_scopes(self) -> None:
        def visit(node: ast.AST, stack: list[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    qual = ".".join(stack + [child.name])
                    self._scopes.append(
                        (qual, child.lineno, child.end_lineno or child.lineno)
                    )
                    visit(child, stack + [child.name])
                else:
                    visit(child, stack)

        visit(self.tree, [])

    def scope_at(self, line: int) -> str:
        best = "<module>"
        best_span = None
        for qual, lo, hi in self._scopes:
            if lo <= line <= hi:
                span = hi - lo
                if best_span is None or span <= best_span:
                    best, best_span = qual, span
        return best


class Project:
    """The package file set plus the cross-file indexes checkers share."""

    def __init__(self, root: Path, package_dirs: Iterable[str]):
        self.root = root
        self.files: list[SourceFile] = []
        self.parse_errors: list[str] = []
        for pkg in package_dirs:
            base = root / pkg
            for path in sorted(base.rglob("*.py")):
                try:
                    self.files.append(SourceFile(path, root))
                except (SyntaxError, UnicodeDecodeError) as e:
                    self.parse_errors.append(f"{path}: unparseable: {e}")
        # names of classes that (transitively, by name) subclass Actor
        self.actor_classes: set[str] = self._find_actor_classes()
        # function/method names carrying @executor_safe anywhere in the
        # project — name-granular on purpose: the checkers resolve
        # attributes (`self.solver.collect_route_db`) by terminal name
        self.executor_safe_names: set[str] = self._find_executor_safe()

    def file(self, rel: str) -> Optional[SourceFile]:
        for f in self.files:
            if f.rel == rel:
                return f
        return None

    def _find_actor_classes(self) -> set[str]:
        bases: dict[str, set[str]] = {}
        for f in self.files:
            for node in ast.walk(f.tree):
                if isinstance(node, ast.ClassDef):
                    names = set()
                    for b in node.bases:
                        if isinstance(b, ast.Name):
                            names.add(b.id)
                        elif isinstance(b, ast.Attribute):
                            names.add(b.attr)
                    bases[node.name] = names
        actors = {"Actor"}
        changed = True
        while changed:
            changed = False
            for cls, parents in bases.items():
                if cls not in actors and parents & actors:
                    actors.add(cls)
                    changed = True
        return actors

    def _find_executor_safe(self) -> set[str]:
        safe: set[str] = set()
        for f in self.files:
            for node in ast.walk(f.tree):
                if not isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                for dec in node.decorator_list:
                    name = None
                    if isinstance(dec, ast.Name):
                        name = dec.id
                    elif isinstance(dec, ast.Attribute):
                        name = dec.attr
                    if name == "executor_safe":
                        safe.add(node.name)
        return safe


@dataclass
class Allowlist:
    path: Path
    entries: dict[str, str] = field(default_factory=dict)  # key -> reason
    used: set[str] = field(default_factory=set)
    errors: list[str] = field(default_factory=list)

    @classmethod
    def load(cls, path: Path) -> "Allowlist":
        al = cls(path=path)
        if not path.exists():
            return al
        data = json.loads(path.read_text())
        for ent in data.get("entries", []):
            key = ent.get("key", "")
            reason = (ent.get("reason") or "").strip()
            if not key:
                al.errors.append(f"{path}: entry without a key: {ent!r}")
                continue
            if not reason:
                al.errors.append(
                    f"{path}: entry {key!r} has no reason — say why"
                )
                continue
            if key in al.entries:
                al.errors.append(f"{path}: duplicate key {key!r}")
            al.entries[key] = reason
        return al

    def matches(self, finding: Finding) -> bool:
        if finding.key in self.entries:
            self.used.add(finding.key)
            return True
        return False

    def unused(self) -> list[str]:
        return sorted(set(self.entries) - self.used)


def apply_suppressions(
    findings: list[Finding], project: Project, allowlist: Allowlist
) -> list[Finding]:
    """Pragma- and allowlist-filter `findings`; returns what remains."""
    out = []
    by_rel = {f.rel: f for f in project.files}
    for fd in findings:
        sf = by_rel.get(fd.path)
        if sf is not None and sf.suppressed(fd.code, fd.line):
            continue
        if allowlist.matches(fd):
            continue
        out.append(fd)
    return out
