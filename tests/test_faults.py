"""Fault-injection registry, supervised-fiber restart, and solver-failover
unit tests (ISSUE 4 tentpole). System-level drills live in test_chaos.py;
this file is tier-1 safe (no network meshes, sub-second runtimes).
"""

import asyncio
import time

import pytest

from openr_tpu.config import (
    DecisionConfig,
    FaultInjectionConfig,
    WatchdogConfig,
)
from openr_tpu.decision.decision import Decision
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.messaging import ReplicateQueue
from openr_tpu.runtime.actor import Actor
from openr_tpu.runtime.counters import counters
from openr_tpu.runtime.faults import (
    FaultInjected,
    maybe_fail,
    registry,
)
from openr_tpu.runtime.monitor import Watchdog
from openr_tpu.runtime.tasks import recent_crashes
from openr_tpu.runtime.tracing import tracer
from openr_tpu.types import (
    Adjacency,
    AdjacencyDatabase,
    PrefixDatabase,
    PrefixEntry,
)
from tests.conftest import run_async


def _counter(key):
    return counters.get_counter(key) or 0


# ---------------------------------------------------------------------------
# registry schedules
# ---------------------------------------------------------------------------

class TestFaultRegistry:
    def teardown_method(self):
        registry.clear()

    def test_idle_site_is_noop(self):
        registry.clear()
        maybe_fail("solver.exec")  # nothing armed: must not raise

    def test_unconditional_fire_and_counters(self):
        base = _counter("runtime.fault.rpc.send.fired")
        registry.arm("rpc.send")
        with pytest.raises(FaultInjected) as ei:
            maybe_fail("rpc.send")
        assert ei.value.site == "rpc.send"
        assert isinstance(ei.value, ConnectionError)
        assert _counter("runtime.fault.rpc.send.fired") == base + 1
        # other sites unaffected
        maybe_fail("fib.program")

    def test_every_nth(self):
        registry.arm("queue.push", every_nth=3)
        fired = []
        for i in range(9):
            try:
                maybe_fail("queue.push")
                fired.append(False)
            except FaultInjected:
                fired.append(True)
        assert fired == [False, False, True] * 3

    def test_one_shot_disarms_after_single_fire(self):
        registry.arm("fib.program", one_shot=True)
        with pytest.raises(FaultInjected):
            maybe_fail("fib.program")
        maybe_fail("fib.program")  # disarmed
        assert registry.list()["armed"] == []

    def test_max_fires(self):
        registry.arm("solver.exec", max_fires=2)
        for _ in range(2):
            with pytest.raises(FaultInjected):
                maybe_fail("solver.exec")
        maybe_fail("solver.exec")
        assert registry.list()["armed"] == []

    def test_probability_deterministic_for_seed(self):
        def pattern(seed):
            registry.arm("kvstore.flood", probability=0.5, seed=seed)
            out = []
            for _ in range(64):
                try:
                    maybe_fail("kvstore.flood")
                    out.append(0)
                except FaultInjected:
                    out.append(1)
            registry.clear("kvstore.flood")
            return out

        a = pattern(seed=42)
        b = pattern(seed=42)
        assert a == b
        assert 0 < sum(a) < 64  # actually probabilistic, not degenerate

    def test_window_expires(self):
        registry.arm("rpc.send", window_s=0.02)
        time.sleep(0.05)
        maybe_fail("rpc.send")  # expired: no raise, schedule dropped
        assert registry.list()["armed"] == []

    def test_clear_and_list_shapes(self):
        registry.arm("rpc.send", every_nth=2)
        registry.arm("solver.exec")
        listed = registry.list()
        assert {s["site"] for s in listed["armed"]} == {
            "rpc.send", "solver.exec"
        }
        assert "solver.exec" in listed["known_sites"]
        assert registry.clear("rpc.send") == {"cleared": ["rpc.send"]}
        assert registry.clear("rpc.send") == {"cleared": []}
        assert registry.clear() == {"cleared": ["solver.exec"]}

    def test_span_stamped_on_fire(self):
        class FakeSpan:
            attributes = {}

        sp = FakeSpan()
        registry.arm("solver.exec", one_shot=True)
        with pytest.raises(FaultInjected):
            maybe_fail("solver.exec", span=sp)
        assert sp.attributes["fault_injected"] == "solver.exec"

    def test_arm_validation(self):
        with pytest.raises(ValueError):
            registry.arm("")
        with pytest.raises(ValueError):
            registry.arm("rpc.send", probability=1.5)
        with pytest.raises(ValueError):
            registry.arm("rpc.send", every_nth=-1)
        with pytest.raises(ValueError):
            registry.arm("rpc.send", rate=-1.0)
        # rate is its own schedule — mixing with per-check schedules
        # would make the storm's pacing ambiguous
        with pytest.raises(ValueError):
            registry.arm("rpc.send", rate=10.0, probability=0.5)
        with pytest.raises(ValueError):
            registry.arm("rpc.send", rate=10.0, every_nth=3)

    def test_rate_schedule_paces_a_sustained_storm(self):
        """ISSUE 19 satellite: `rate` fires at the target events/s no
        matter how hot the check loop spins — a token bucket with
        capacity one (no burst debt), not a per-call coin flip."""
        registry.arm("decision.ingest", rate=200.0)
        fired = 0
        t0 = time.monotonic()
        # spin far faster than 200 Hz for ~0.1 s
        while time.monotonic() - t0 < 0.1:
            try:
                maybe_fail("decision.ingest")
            except FaultInjected:
                fired += 1
        registry.clear("decision.ingest")
        # 0.1 s at 200/s -> ~20 firings + the initial full token;
        # generous bounds absorb scheduler jitter
        assert 10 <= fired <= 35, fired

    def test_rate_schedule_no_burst_debt_after_quiet_stretch(self):
        registry.arm("fib.program", rate=1000.0)
        with pytest.raises(FaultInjected):
            maybe_fail("fib.program")  # initial token
        time.sleep(0.05)  # 50 tokens' worth of quiet time...
        fired = 0
        for _ in range(10):
            try:
                maybe_fail("fib.program")
            except FaultInjected:
                fired += 1
        registry.clear("fib.program")
        # ...but the bucket caps at ONE token: no catch-up burst
        assert fired <= 2, fired

    def test_configure_from_config(self):
        registry.configure(
            FaultInjectionConfig(
                enable_fault_injection=True,
                seed=7,
                schedules=[{"site": "rpc.send", "every_nth": 2}],
            )
        )
        assert registry.seed == 7
        assert registry.list()["armed"][0]["site"] == "rpc.send"
        # disabled config clears everything
        registry.configure(FaultInjectionConfig(seed=0))
        assert registry.list()["armed"] == []


# ---------------------------------------------------------------------------
# supervised fibers
# ---------------------------------------------------------------------------

class _FlakyActor(Actor):
    """Supervised fiber that crashes `crashes` times, then parks forever."""

    def __init__(self, crashes=2):
        super().__init__("flaky")
        self.restart_backoff_initial_s = 0.01
        self.restart_backoff_max_s = 0.02
        self.crashes = crashes
        self.attempts = 0
        self.recoveries = []
        self.healthy = asyncio.Event()

    async def on_start(self):
        self.add_supervised_task(self._work, name="flaky.work")

    async def on_fiber_restart(self, task_name):
        self.recoveries.append(task_name)

    async def _work(self):
        self.attempts += 1
        if self.attempts <= self.crashes:
            raise RuntimeError(f"boom {self.attempts}")
        self.healthy.set()
        await asyncio.Event().wait()


class TestSupervisor:
    @run_async
    async def test_restart_within_budget(self):
        base = _counter("runtime.supervisor.restarts")
        a = _FlakyActor(crashes=2)
        await a.start()
        try:
            await asyncio.wait_for(a.healthy.wait(), timeout=5)
        finally:
            await a.stop()
        assert a.attempts == 3
        assert a.recoveries == ["flaky.work", "flaky.work"]
        assert _counter("runtime.supervisor.restarts") >= base + 2
        assert _counter("runtime.supervisor.restarts.flaky") >= 2

    @run_async
    async def test_crash_budget_exhaustion_escalates(self):
        escalated = []
        a = _FlakyActor(crashes=1000)
        a.crash_budget = 2
        a._escalate = escalated.append
        base = _counter("runtime.supervisor.escalations")
        await a.start()
        try:
            for _ in range(250):
                if escalated:
                    break
                await asyncio.sleep(0.02)
        finally:
            await a.stop()
        assert escalated and "crash budget" in escalated[0]
        assert a.attempts == 3  # budget 2 -> two restarts, third crash fatal
        assert _counter("runtime.supervisor.escalations") >= base + 1

    @run_async
    async def test_watchdog_wires_supervisor_and_fires(self):
        fired = []
        wd = Watchdog(
            "node1",
            WatchdogConfig(
                supervisor_crash_budget=0,
                supervisor_backoff_initial_s=0.01,
                supervisor_backoff_max_s=0.02,
            ),
            crash_handler=fired.append,
        )
        a = _FlakyActor(crashes=1000)
        wd.watch_actor(a)
        assert a.crash_budget == 0
        assert a._escalate is not None
        await a.start()
        try:
            for _ in range(250):
                if fired:
                    break
                await asyncio.sleep(0.02)
        finally:
            await a.stop()
        assert fired and wd.fired is not None
        assert "flaky.work" in wd.fired

    @run_async
    async def test_crashes_land_in_ring_and_counters(self):
        base = _counter("runtime.task_crash.flaky.work")
        a = _FlakyActor(crashes=1)
        await a.start()
        try:
            await asyncio.wait_for(a.healthy.wait(), timeout=5)
        finally:
            await a.stop()
        assert _counter("runtime.task_crash.flaky.work") == base + 1
        ring = recent_crashes()
        assert any(
            c["task"] == "flaky.work" and "boom 1" in c["error"]
            for c in ring
        )

    @run_async
    async def test_shutdown_is_not_a_crash(self):
        base = _counter("runtime.supervisor.restarts")
        a = _FlakyActor(crashes=0)
        await a.start()
        await asyncio.wait_for(a.healthy.wait(), timeout=5)
        await a.stop()  # cancellation must not burn crash budget
        assert a._crash_count == 0
        assert _counter("runtime.supervisor.restarts") == base


# ---------------------------------------------------------------------------
# solver failover (Decision._solve_full / probe / promote)
# ---------------------------------------------------------------------------

class FlakySolver:
    """TpuSpfSolver stand-in: a primary that can be forced down, carrying
    the CPU oracle as its `cpu` fallback (the failover contract)."""

    def __init__(self, node_name):
        self.cpu = SpfSolver(node_name)
        self.fail = False
        self.primary_builds = 0

    def build_route_db(self, *args, **kwargs):
        if self.fail:
            raise RuntimeError("device lost")
        self.primary_builds += 1
        return self.cpu.build_route_db(*args, **kwargs)

    def update_static_unicast_routes(self, update):
        self.cpu.update_static_unicast_routes(update)

    def create_route_for_prefix_or_get_static(self, *args):
        return self.cpu.create_route_for_prefix_or_get_static(*args)


def _two_node_state():
    ls = LinkState("0")
    ls.update_adjacency_database(
        AdjacencyDatabase(
            this_node_name="a",
            adjacencies=(
                Adjacency(
                    other_node_name="b", if_name="i0", other_if_name="i1"
                ),
            ),
            area="0",
        )
    )
    ls.update_adjacency_database(
        AdjacencyDatabase(
            this_node_name="b",
            adjacencies=(
                Adjacency(
                    other_node_name="a", if_name="i1", other_if_name="i0"
                ),
            ),
            area="0",
        )
    )
    ps = PrefixState()
    ps.update_prefix_database(
        PrefixDatabase(
            this_node_name="b",
            prefix_entries=(PrefixEntry(prefix="10.0.0.2/32"),),
            area="0",
        )
    )
    return ls, ps


def _make_decision():
    kq = ReplicateQueue("kv")
    rq = ReplicateQueue("routes")
    d = Decision(
        "a",
        DecisionConfig(
            debounce_min_ms=5,
            debounce_max_ms=25,
            solver_probe_initial_backoff_s=0.01,
            solver_probe_max_backoff_s=0.05,
        ),
        kq.get_reader("decision"),
        None,
        rq,
        solver_backend="cpu",
    )
    d.solver = FlakySolver("a")
    ls, ps = _two_node_state()
    d.area_link_states = {"0": ls}
    d.prefix_state = ps
    d._kvstore_synced = True
    return d


class TestSolverFailover:
    def setup_method(self):
        registry.clear()
        counters.set_counter("decision.solver.degraded", 0)

    def teardown_method(self):
        registry.clear()
        counters.set_counter("decision.solver.degraded", 0)

    @run_async
    async def test_failover_then_promotion(self):
        d = _make_decision()
        failovers0 = _counter("decision.solver.failovers")
        promotions0 = _counter("decision.solver.promotions")
        d.solver.fail = True
        d.pending.needs_full_rebuild = True
        ctx = tracer.start_trace("adj_update", node="a")
        d.pending.trace = ctx
        try:
            d.rebuild_routes()
            # failed over mid-flight: routes still built, via the oracle
            assert d._degraded
            assert "10.0.0.2/32" in d.route_db.unicast_routes
            assert _counter("decision.solver.degraded") == 1
            assert _counter("decision.solver.failovers") == failovers0 + 1
            # trace root carries the degraded stamp
            [tr] = tracer.get_traces(
                trace_id=ctx.trace_id, include_active=True
            )
            assert tr["spans"][0]["attributes"].get("degraded") is True
            # primary still down: probe fails, stays degraded
            await asyncio.sleep(0.05)
            assert d._degraded
            assert _counter("decision.solver.probe_failures") >= 1
            # primary heals: backoff-timed canary promotes it back
            d.solver.fail = False
            for _ in range(200):
                if not d._degraded:
                    break
                await asyncio.sleep(0.02)
            assert not d._degraded
            assert _counter("decision.solver.degraded") == 0
            assert _counter("decision.solver.promotions") == promotions0 + 1
        finally:
            tracer.end_trace(ctx, status="test_done")
            for t in list(d._timers):
                t.cancel()

    @run_async
    async def test_fault_site_drives_failover(self):
        """solver.exec armed via the registry: the same drill `breeze
        fault inject solver.exec` runs against a live node."""
        d = _make_decision()
        registry.arm("solver.exec", one_shot=True)
        d.pending.needs_full_rebuild = True
        try:
            d.rebuild_routes()
            assert d._degraded
            assert "10.0.0.2/32" in d.route_db.unicast_routes
            assert d.solver.primary_builds == 0  # primary never completed
            # one_shot disarmed on fire -> probe path is clean; FlakySolver
            # has no probe_device, so the canary topology solve promotes
            for _ in range(200):
                if not d._degraded:
                    break
                await asyncio.sleep(0.02)
            assert not d._degraded
            assert d.solver.primary_builds >= 1  # canary ran the primary
            assert _counter("runtime.fault.solver.exec.fired") >= 1
        finally:
            for t in list(d._timers):
                t.cancel()

    @run_async
    async def test_cpu_backend_without_fallback_reraises(self):
        d = _make_decision()
        d.solver = SpfSolver("a")  # no .cpu attribute: no failover seam
        registry.arm("solver.exec", one_shot=True)
        d.pending.needs_full_rebuild = True
        with pytest.raises(FaultInjected):
            d.rebuild_routes()
        assert not d._degraded
