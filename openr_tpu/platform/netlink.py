"""Async rtnetlink client — the kernel boundary.

Role of the reference's openr/nl/NetlinkProtocolSocket.{h,cpp}: an
asyncio AF_NETLINK/NETLINK_ROUTE socket with sequence-numbered request
pipelining (ack futures, bounded in-flight window — ref h:33-70),
multipart dump parsing, and RTM_NEWROUTE/RTM_DELROUTE/RTM_GETROUTE
message (de)serialization with RTA attributes incl. RTA_MULTIPATH ECMP
next-hop groups (ref NetlinkRouteMessage.cpp). Implemented directly on
the kernel's binary netlink ABI via struct packing — no external
dependencies.

Route add/delete requires CAP_NET_ADMIN; dumps are unprivileged. The
platform FibHandler (fib_handler.py) drives this behind the dataplane
seam; tests gate kernel-mutating cases on capability.
"""

from __future__ import annotations

import asyncio
import ipaddress
import socket
import struct
from dataclasses import dataclass, field
from typing import Optional

# netlink message types / flags (linux/netlink.h)
NLMSG_ERROR = 2
NLMSG_DONE = 3
NLM_F_REQUEST = 0x01
NLM_F_MULTI = 0x02
NLM_F_ACK = 0x04
NLM_F_ROOT = 0x100
NLM_F_MATCH = 0x200
NLM_F_DUMP = NLM_F_ROOT | NLM_F_MATCH
NLM_F_REPLACE = 0x100
NLM_F_CREATE = 0x400

# rtnetlink (linux/rtnetlink.h)
RTM_NEWROUTE = 24
RTM_DELROUTE = 25
RTM_GETROUTE = 26
RTN_UNICAST = 1
RT_SCOPE_UNIVERSE = 0
RT_TABLE_MAIN = 254

RTA_DST = 1
RTA_OIF = 4
RTA_GATEWAY = 5
RTA_PRIORITY = 6
RTA_MULTIPATH = 9
RTA_TABLE = 15

_NLMSGHDR = struct.Struct("=IHHII")  # len, type, flags, seq, pid
_RTMSG = struct.Struct("=BBBBBBBBI")  # family,dst,src,tos,table,proto,scope,type,flags
_RTA = struct.Struct("=HH")  # len, type
_RTNH = struct.Struct("=HBBi")  # len, flags, hops, ifindex

# protocol id this daemon stamps on its routes (ref kRouteProtoId role)
PROTO_OPENR = 99


def _align4(n: int) -> int:
    return (n + 3) & ~3


def _rta(rta_type: int, payload: bytes) -> bytes:
    length = _RTA.size + len(payload)
    return _RTA.pack(length, rta_type) + payload + b"\0" * (
        _align4(length) - length
    )


@dataclass(frozen=True)
class NlNextHop:
    """One kernel next hop: gateway address and/or output interface."""

    gateway: Optional[str] = None  # "10.0.0.1" / "fe80::1"
    ifindex: int = 0
    weight: int = 0  # ECMP weight hint (rtnh_hops = weight - 1)


@dataclass
class NlRoute:
    prefix: str
    nexthops: tuple = ()
    metric: int = 0
    table: int = RT_TABLE_MAIN
    protocol: int = PROTO_OPENR

    @property
    def family(self) -> int:
        return (
            socket.AF_INET
            if ipaddress.ip_network(self.prefix, strict=False).version == 4
            else socket.AF_INET6
        )


@dataclass
class _Pending:
    future: asyncio.Future
    dump: bool = False
    results: list = field(default_factory=list)


class NetlinkRouteSocket:
    """Pipelined rtnetlink requests (ref NetlinkProtocolSocket.h:33-70:
    up to `max_in_flight` un-acked requests, each completing its future
    on ACK/ERROR/DONE)."""

    def __init__(self, max_in_flight: int = 256):
        self._sock: Optional[socket.socket] = None
        self._seq = 0
        self._pending: dict[int, _Pending] = {}
        self._window = asyncio.Semaphore(max_in_flight)
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    # -- lifecycle ---------------------------------------------------------

    def open(self) -> None:
        sock = socket.socket(
            socket.AF_NETLINK, socket.SOCK_RAW, socket.NETLINK_ROUTE
        )
        sock.bind((0, 0))
        sock.setblocking(False)
        self._sock = sock
        self._loop = asyncio.get_running_loop()
        self._loop.add_reader(sock.fileno(), self._on_readable)

    def close(self) -> None:
        if self._sock is not None:
            if self._loop is not None:
                self._loop.remove_reader(self._sock.fileno())
            self._sock.close()
            self._sock = None
        for p in self._pending.values():
            # _complete() releases a window slot per answered request;
            # failing un-answered ones here bypasses it, and without a
            # matching release a close with in-flight requests permanently
            # shrinks the window if the socket is reopened. Already-done
            # futures (answered, not yet reaped by _send) released theirs
            # in _complete — skip them or the slot double-releases.
            if not p.future.done():
                p.future.set_exception(ConnectionError("netlink closed"))
                self._window.release()
            elif p.future.cancelled():
                # timed-out request whose _send finally hasn't run yet:
                # _complete never released its slot, and after we clear
                # _pending the finally's pop comes back empty so IT won't
                # release either — do it here
                self._window.release()
        self._pending.clear()

    # -- request plumbing --------------------------------------------------

    async def _send(self, msg_type: int, flags: int, payload: bytes,
                    dump: bool = False) -> list:
        assert self._sock is not None, "open() first"
        await self._window.acquire()
        self._seq += 1
        seq = self._seq
        hdr = _NLMSGHDR.pack(
            _NLMSGHDR.size + len(payload), msg_type, flags, seq, 0
        )
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[seq] = _Pending(fut, dump=dump)
        try:
            self._sock.send(hdr + payload)
        except OSError:
            self._pending.pop(seq, None)
            self._window.release()
            raise
        try:
            return await asyncio.wait_for(fut, 5.0)
        finally:
            # a timed-out request still holds a window slot (_complete
            # releases only for answered requests) — release it here, or
            # lost kernel replies would leak slots until every _send
            # deadlocks in acquire(). wait_for CANCELS the future on
            # timeout (a cancelled future reads as done), so the "did
            # _complete ever run" test is cancelled(), not done().
            if self._pending.pop(seq, None) is not None and fut.cancelled():
                self._window.release()

    def _on_readable(self) -> None:
        assert self._sock is not None
        try:
            data = self._sock.recv(1 << 17)
        except (BlockingIOError, InterruptedError):
            return
        except OSError as e:
            # ENOBUFS means the kernel dropped replies — the affected
            # seqs are unknowable, so fail every in-flight request (each
            # failure releases its window slot) rather than letting them
            # all time out against a silently-lost ack
            for seq in list(self._pending):
                self._complete(seq, error=e.errno or 105)
            return
        off = 0
        while off + _NLMSGHDR.size <= len(data):
            mlen, mtype, mflags, seq, _pid = _NLMSGHDR.unpack_from(data, off)
            if mlen < _NLMSGHDR.size:
                break
            body = data[off + _NLMSGHDR.size:off + mlen]
            self._on_msg(mtype, mflags, seq, body)
            off += _align4(mlen)

    def _complete(self, seq: int, value=None, error: Optional[int] = None):
        p = self._pending.get(seq)
        if p is None or p.future.done():
            return
        self._window.release()
        if error:
            p.future.set_exception(
                OSError(error, f"netlink error {error} (seq {seq})")
            )
        else:
            p.future.set_result(p.results if p.dump else value)

    def _on_msg(self, mtype: int, mflags: int, seq: int, body: bytes):
        if mtype == NLMSG_ERROR:
            (code,) = struct.unpack_from("=i", body)
            self._complete(seq, error=-code if code else None)
        elif mtype == NLMSG_DONE:
            self._complete(seq)
        else:
            p = self._pending.get(seq)
            if p is not None and p.dump:
                route = _parse_route_msg(body)
                if route is not None:
                    p.results.append(route)
                if not (mflags & NLM_F_MULTI):
                    self._complete(seq)

    # -- route operations (ref addRoute/deleteRoute/getAllRoutes) ----------

    async def add_route(self, route: NlRoute, replace: bool = True) -> None:
        flags = NLM_F_REQUEST | NLM_F_ACK | NLM_F_CREATE
        if replace:
            flags |= NLM_F_REPLACE
        await self._send(RTM_NEWROUTE, flags, _build_route_msg(route))

    async def delete_route(self, route: NlRoute) -> None:
        await self._send(
            RTM_DELROUTE,
            NLM_F_REQUEST | NLM_F_ACK,
            _build_route_msg(route, for_delete=True),
        )

    async def get_routes(self, family: int = socket.AF_INET,
                         table: Optional[int] = None,
                         protocol: Optional[int] = None) -> list[NlRoute]:
        rtm = _RTMSG.pack(family, 0, 0, 0, 0, 0, 0, 0, 0)
        routes = await self._send(
            RTM_GETROUTE, NLM_F_REQUEST | NLM_F_DUMP, rtm, dump=True
        )
        return [
            r
            for r in routes
            if (table is None or r.table == table)
            and (protocol is None or r.protocol == protocol)
        ]


def native_bulk_available() -> bool:
    """True when the C++ bulk programmer (native/netlink_bulk.cpp, built
    via native/build_native.py) is importable."""
    try:
        import openr_tpu_native  # noqa: F401
    except ImportError:
        return False
    return True


def pack_bulk_routes(routes: list[NlRoute]) -> bytes:
    """Pack NlRoutes into the native module's record format (see
    native/netlink_bulk.cpp header comment).

    Raises ValueError when a gateway's family differs from the route's:
    the native encoder sizes RTA_GATEWAY from the ROUTE family, and a
    truncated v6 gateway on a v4 route would be ACCEPTED by the kernel
    as a garbage v4 gateway (silent black hole) — the caller falls back
    to the per-route path, which reports such routes as failed."""
    out = bytearray()
    for r in routes:
        net = ipaddress.ip_network(r.prefix, strict=False)
        family = socket.AF_INET if net.version == 4 else socket.AF_INET6
        nhs = r.nexthops or (NlNextHop(),)
        if len(nhs) > 255:
            raise ValueError(
                f"{r.prefix}: {len(nhs)} nexthops exceed the bulk "
                "format's u8 count"
            )
        out += struct.pack(
            "<BBBBI", family, net.prefixlen, len(nhs), 0, r.metric
        )
        out += net.network_address.packed.ljust(16, b"\0")
        for nh in nhs:
            gw = b""
            if nh.gateway:
                addr = ipaddress.ip_address(nh.gateway)
                if addr.version != net.version:
                    raise ValueError(
                        f"{r.prefix}: gateway {nh.gateway} family differs "
                        "from route family (bulk path cannot encode it)"
                    )
                gw = addr.packed
            out += struct.pack("<II", nh.ifindex, nh.weight)
            out += gw.ljust(16, b"\0")
    return bytes(out)


def bulk_route_op(
    op: int, table: int, protocol: int, routes: list[NlRoute]
) -> tuple[int, int]:
    """(ok, err) — whole pipeline (encode, pipelined send, ack harvest)
    in C++ (role of openr/nl's native fast path; measured ~150k routes/s
    vs the reference's stated 100k < 2s, NetlinkProtocolSocket.h:69-70).
    op: 0 = add/replace, 1 = delete."""
    import openr_tpu_native

    return openr_tpu_native.bulk_route_op(
        op, table, protocol, pack_bulk_routes(routes)
    )


def _build_route_msg(route: NlRoute, for_delete: bool = False) -> bytes:
    net = ipaddress.ip_network(route.prefix, strict=False)
    family = socket.AF_INET if net.version == 4 else socket.AF_INET6
    table = route.table if route.table < 256 else RT_TABLE_MAIN
    rtm = _RTMSG.pack(
        family,
        net.prefixlen,
        0,
        0,
        table,
        route.protocol,
        RT_SCOPE_UNIVERSE,
        RTN_UNICAST,
        0,
    )
    attrs = [_rta(RTA_DST, net.network_address.packed)]
    if route.table >= 256:
        attrs.append(_rta(RTA_TABLE, struct.pack("=I", route.table)))
    if route.metric:
        attrs.append(_rta(RTA_PRIORITY, struct.pack("=I", route.metric)))
    nhs = route.nexthops
    if not for_delete and nhs:
        if len(nhs) == 1:
            nh = nhs[0]
            if nh.gateway:
                attrs.append(
                    _rta(
                        RTA_GATEWAY,
                        ipaddress.ip_address(nh.gateway).packed,
                    )
                )
            if nh.ifindex:
                attrs.append(_rta(RTA_OIF, struct.pack("=i", nh.ifindex)))
        else:
            # ECMP group: rtnexthop records, each with nested RTAs
            blob = b""
            for nh in nhs:
                nested = b""
                if nh.gateway:
                    nested = _rta(
                        RTA_GATEWAY, ipaddress.ip_address(nh.gateway).packed
                    )
                rtnh_len = _RTNH.size + len(nested)
                blob += _RTNH.pack(
                    rtnh_len, 0, max(nh.weight - 1, 0), nh.ifindex
                ) + nested
            attrs.append(_rta(RTA_MULTIPATH, blob))
    return rtm + b"".join(attrs)


def _parse_route_msg(body: bytes) -> Optional[NlRoute]:
    if len(body) < _RTMSG.size:
        return None
    family, dst_len, _src, _tos, table, proto, _scope, rtype, _flags = (
        _RTMSG.unpack_from(body)
    )
    if family not in (socket.AF_INET, socket.AF_INET6):
        return None
    dst = None
    metric = 0
    nexthops: list[NlNextHop] = []
    gateway = None
    oif = 0
    off = _RTMSG.size
    while off + _RTA.size <= len(body):
        alen, atype = _RTA.unpack_from(body, off)
        if alen < _RTA.size:
            break
        payload = body[off + _RTA.size:off + alen]
        if atype == RTA_DST:
            dst = payload
        elif atype == RTA_PRIORITY and len(payload) >= 4:
            (metric,) = struct.unpack("=I", payload[:4])
        elif atype == RTA_TABLE and len(payload) >= 4:
            (table,) = struct.unpack("=I", payload[:4])
        elif atype == RTA_GATEWAY:
            gateway = str(ipaddress.ip_address(payload))
        elif atype == RTA_OIF and len(payload) >= 4:
            (oif,) = struct.unpack("=i", payload[:4])
        elif atype == RTA_MULTIPATH:
            noff = 0
            while noff + _RTNH.size <= len(payload):
                rtnh_len, _f, hops, ifindex = _RTNH.unpack_from(payload, noff)
                if rtnh_len < _RTNH.size:
                    break
                gw = None
                aoff = noff + _RTNH.size
                while aoff + _RTA.size <= noff + rtnh_len:
                    nlen, ntype = _RTA.unpack_from(payload, aoff)
                    if nlen < _RTA.size:
                        break
                    if ntype == RTA_GATEWAY:
                        gw = str(
                            ipaddress.ip_address(
                                payload[aoff + _RTA.size:aoff + nlen]
                            )
                        )
                    aoff += _align4(nlen)
                nexthops.append(
                    NlNextHop(gateway=gw, ifindex=ifindex, weight=hops + 1)
                )
                noff += _align4(rtnh_len)
        off += _align4(alen)
    if gateway or oif:
        nexthops.append(NlNextHop(gateway=gateway, ifindex=oif))
    if dst is None:
        addr = "0.0.0.0" if family == socket.AF_INET else "::"
    else:
        addr = str(ipaddress.ip_address(dst))
    return NlRoute(
        prefix=f"{addr}/{dst_len}",
        nexthops=tuple(nexthops),
        metric=metric,
        table=table,
        protocol=proto,
    )
