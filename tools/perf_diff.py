"""Per-headline perf verdicts between two bench JSONs, or a bench JSON
and the perf ledger.

    python -m tools.perf_diff BENCH_r05.json bench-smoke.json
    python -m tools.perf_diff --ledger <ledger-dir> bench-new.json

Both bench output shapes (quick and full) flatten to dotted numeric
paths; each path present in BOTH inputs gets a verdict:

    improved    better by more than --threshold (fractional)
    regressed   worse by more than --threshold
    neutral     within the threshold band

Direction is inferred from the key: `*_ms` / `*_mb` / `*_s` / `value`
are lower-better; speedup-style keys are higher-better; anything else is
compared but only reported (never a verdict) — a count changing is a
fact, not a regression. Values below --min-value on both sides are
skipped: a 0.4 ms metric doubling on a shared CI runner is noise, not a
regression. Exit status is the CI contract: 0 when nothing regressed,
1 otherwise.

Ledger mode compares the flattened bench metrics against the stored
quantile baselines for matching kernel keys (see runtime/perf_ledger.py
for the key scheme).
"""

from __future__ import annotations

import argparse
import json
import sys

# keys where MORE is better; everything else numeric-lower-better is
# inferred from its unit suffix
HIGHER_BETTER = {
    "speedup",
    "device_speedup",
    "vs_baseline",
    "scenarios_per_s",
    "overlap_efficiency",
    "solves",
    # AOT executable cache (ISSUE 20): fraction of warm-boot lookups
    # served from the serialized-executable disk cache
    "aot_hit_rate",
}
LOWER_BETTER_SUFFIXES = ("_ms", "_mb", "_s", "_bytes")


def direction(key: str) -> str:
    """'lower' / 'higher' / 'info' for one dotted path's leaf key."""
    leaf = key.rsplit(".", 1)[-1]
    if leaf in HIGHER_BETTER:
        return "higher"
    if leaf == "value" or leaf.endswith(LOWER_BETTER_SUFFIXES):
        return "lower"
    return "info"


def flatten(doc, prefix: str = "") -> dict[str, float]:
    """Numeric leaves of a nested JSON document as dotted paths."""
    out: dict[str, float] = {}
    if isinstance(doc, dict):
        for k, v in doc.items():
            out.update(flatten(v, f"{prefix}{k}."))
    elif isinstance(doc, list):
        for i, v in enumerate(doc):
            out.update(flatten(v, f"{prefix}{i}."))
    elif isinstance(doc, (int, float)) and not isinstance(doc, bool):
        out[prefix[:-1]] = float(doc)
    return out


def lanes_of(flat: dict[str, float]) -> set[str]:
    """Bench lane (config) names present in a flattened bench doc —
    every `configs.<name>.*` path contributes <name>."""
    lanes = set()
    for key in flat:
        if key.startswith("configs."):
            rest = key[len("configs."):]
            if "." in rest:
                lanes.add(rest.split(".", 1)[0])
    return lanes


def vanished_lane_rows(
    baseline: dict[str, float],
    candidate: dict[str, float],
    expect_lanes: set[str] | None = None,
) -> list[dict]:
    """A lane present in the baseline but absent from the candidate is
    an explicit regression, not a neutral skip — a silently-skipped
    bench config must not pass the CI gate. `expect_lanes` narrows the
    check (a smoke gate that only runs mesh4 passes --expect-lanes
    mesh4); None means every baseline lane is expected."""
    base_lanes = lanes_of(baseline)
    cand_lanes = lanes_of(candidate)
    expected = base_lanes if expect_lanes is None else (
        base_lanes & set(expect_lanes)
    )
    rows = []
    for lane in sorted(expected - cand_lanes):
        rows.append(
            {
                "metric": f"configs.{lane}",
                "baseline": "present",
                "candidate": "MISSING",
                "delta_pct": None,
                "verdict": "regressed",
            }
        )
    return rows


def compare(
    baseline: dict[str, float],
    candidate: dict[str, float],
    threshold: float,
    min_value: float,
) -> list[dict]:
    rows = []
    for key in sorted(set(baseline) & set(candidate)):
        base, cand = baseline[key], candidate[key]
        if abs(base) < min_value and abs(cand) < min_value:
            continue
        d = direction(key)
        if base == 0:
            delta = 0.0 if cand == 0 else float("inf")
        else:
            delta = (cand - base) / abs(base)
        if d == "info":
            verdict = "info"
        else:
            worse = delta if d == "lower" else -delta
            if worse > threshold:
                verdict = "regressed"
            elif worse < -threshold:
                verdict = "improved"
            else:
                verdict = "neutral"
        rows.append(
            {
                "metric": key,
                "baseline": round(base, 3),
                "candidate": round(cand, 3),
                "delta_pct": (
                    round(delta * 100.0, 1) if delta != float("inf") else None
                ),
                "verdict": verdict,
            }
        )
    return rows


def _load_bench(path: str) -> dict[str, float]:
    with open(path) as f:
        doc = json.load(f)
    # the committed BENCH_rNN baselines wrap the bench line in a driver
    # envelope ({"cmd", "rc", "parsed": {...}}); unwrap so envelope and
    # raw bench outputs flatten to the same paths
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    flat = flatten(doc)
    # skipped configs flatten to nothing numeric; rig_rtt_ms is the
    # tunnel's property, not the code's — never a verdict subject
    return {k: v for k, v in flat.items() if not k.endswith("rig_rtt_ms")}


def _load_ledger(dir_path: str) -> dict[str, float]:
    """Ledger baselines flattened to comparable dotted paths:
    `configs.<name>.<metric>` from `solve[<name>]` default-variant p95s,
    so they line up with a flattened bench JSON."""
    sys.path.insert(0, ".")
    from openr_tpu.runtime import perf_ledger

    lg = perf_ledger.PerfLedger(dir_path)
    out: dict[str, float] = {}
    for key, entry in lg.snapshot()["keys"].items():
        kernel, _sig, variant, _fp = (key.split("|") + [""] * 4)[:4]
        if not (kernel.startswith("solve[") and kernel.endswith("]")):
            continue
        if variant != "default":
            continue
        name = kernel[len("solve["):-1]
        for metric, quantiles in entry["metrics"].items():
            out[f"configs.{name}.{metric}"] = quantiles["p95"]
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="perf-diff", description=__doc__.split("\n")[0]
    )
    p.add_argument("baseline", help="baseline bench JSON (or, with "
                   "--ledger, ignored in favor of the ledger dir)")
    p.add_argument("candidate", nargs="?", default=None,
                   help="candidate bench JSON (defaults to `baseline` "
                   "when --ledger supplies the baseline side)")
    p.add_argument("--ledger", default=None, metavar="DIR",
                   help="compare the candidate bench JSON against the "
                   "perf ledger in DIR instead of a baseline JSON")
    p.add_argument("--threshold", type=float, default=0.25,
                   help="fractional change beyond which a headline is "
                   "improved/regressed (default 0.25 = 25%%)")
    p.add_argument("--min-value", type=float, default=1.0,
                   help="skip metrics below this on both sides — "
                   "sub-threshold timings are runner noise (default 1)")
    p.add_argument("--expect-lanes", default=None, metavar="NAMES",
                   help="comma-separated bench lanes the candidate must "
                   "contain; a listed (or, without this flag, ANY "
                   "baseline) lane missing from the candidate is a "
                   "regression — a skipped config can't pass the gate")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable verdict rows")
    args = p.parse_args(argv)

    if args.ledger:
        base = _load_ledger(args.ledger)
        cand = _load_bench(args.candidate or args.baseline)
    else:
        if args.candidate is None:
            p.error("candidate JSON required without --ledger")
        base = _load_bench(args.baseline)
        cand = _load_bench(args.candidate)

    expect = (
        {s for s in args.expect_lanes.split(",") if s}
        if args.expect_lanes is not None
        else None
    )
    rows = vanished_lane_rows(base, cand, expect)
    rows += compare(base, cand, args.threshold, args.min_value)
    regressed = [r for r in rows if r["verdict"] == "regressed"]
    if args.as_json:
        print(json.dumps({"rows": rows, "regressed": len(regressed)}))
    else:
        width = max((len(r["metric"]) for r in rows), default=10)
        for r in rows:
            if r["verdict"] == "info":
                continue
            mark = {"regressed": "✗", "improved": "✓"}.get(r["verdict"], " ")
            print(
                f"{mark} {r['metric']:<{width}}  "
                f"{r['baseline']:>12} -> {r['candidate']:>12}  "
                f"{'' if r['delta_pct'] is None else r['delta_pct']:>7}%  "
                f"{r['verdict']}"
            )
        print(
            f"{len(rows)} compared, {len(regressed)} regressed "
            f"(threshold {args.threshold:.0%}, floor {args.min_value})"
        )
    return 1 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
