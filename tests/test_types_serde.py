"""Schema + codec round-trip tests (role of thrift serializer tests)."""

from openr_tpu import serde, types


def test_adjacency_db_roundtrip():
    db = types.AdjacencyDatabase(
        this_node_name="node1",
        adjacencies=(
            types.Adjacency("node2", "if_1_2", "if_2_1", metric=10, rtt_us=1200),
            types.Adjacency(
                "node3", "if_1_3", metric=5, adj_only_used_by_other_node=True
            ),
        ),
        is_overloaded=True,
        node_label=101,
        area="area1",
    )
    assert serde.deserialize(serde.serialize(db), types.AdjacencyDatabase) == db


def test_prefix_db_roundtrip():
    db = types.PrefixDatabase(
        this_node_name="node1",
        prefix_entries=(
            types.PrefixEntry(
                prefix="10.1.0.0/16",
                type=types.PrefixType.BGP,
                metrics=types.PrefixMetrics(path_preference=2000),
                forwarding_type=types.PrefixForwardingType.SR_MPLS,
                forwarding_algorithm=types.PrefixForwardingAlgorithm.KSP2_ED_ECMP,
                min_nexthop=2,
                tags=("tag1",),
            ),
        ),
        delete_prefix=False,
    )
    out = serde.deserialize(serde.serialize(db), types.PrefixDatabase)
    assert out == db
    assert out.prefix_entries[0].forwarding_algorithm is (
        types.PrefixForwardingAlgorithm.KSP2_ED_ECMP
    )


def test_kvstore_value_hash_auto():
    v = types.Value(version=3, originator_id="n1", value=b"payload", ttl_ms=5000)
    assert v.hash is not None
    v2 = types.Value(version=3, originator_id="n1", value=b"payload")
    assert v.hash == v2.hash
    v3 = types.Value(version=4, originator_id="n1", value=b"payload")
    assert v.hash != v3.hash


def test_publication_roundtrip():
    pub = types.Publication(
        key_vals={"adj:n1": types.Value(1, "n1", b"x", ttl_ms=100)},
        expired_keys=["prefix:old"],
        node_ids=["n1", "n2"],
        area="0",
    )
    out = serde.deserialize(serde.serialize(pub), types.Publication)
    assert out.key_vals["adj:n1"].value == b"x"
    assert out.node_ids == ["n1", "n2"]


def test_forward_compat_unknown_and_missing_fields():
    import json

    plain = serde.to_plain(types.Adjacency("n2", "if1"))
    plain["brand_new_field"] = 42  # unknown field ignored
    del plain["weight"]  # missing field -> default
    adj = serde.from_plain(plain, types.Adjacency)
    assert adj.other_node_name == "n2"
    assert adj.weight == 1
    json.dumps(plain)


def test_key_naming():
    assert types.adj_key("node-1") == "adj:node-1"
    assert types.parse_adj_key("adj:node-1") == "node-1"
    assert types.parse_adj_key("prefix:x") is None
    k = types.prefix_key("node-1", "area0", "10.0.0.0/24")
    assert types.parse_prefix_key(k) == ("node-1", "area0", "10.0.0.0/24")
    assert types.parse_prefix_key("garbage") is None


def test_spark_packet_roundtrip():
    pkt = types.SparkPacket(
        hello=types.SparkHelloMsg(
            domain_name="d",
            node_name="n1",
            if_name="eth0",
            seq_num=7,
            neighbor_infos={"n2": types.ReflectedNeighborInfo(seq_num=3)},
            solicit_response=True,
        )
    )
    out = serde.deserialize(serde.serialize(pkt), types.SparkPacket)
    assert out.hello.neighbor_infos["n2"].seq_num == 3
    assert out.handshake is None
