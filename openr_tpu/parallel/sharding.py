"""Multi-chip sharding of the route-computation pipeline.

The reference is single-process C++ with no device parallelism; the scale
axis it offers is per-area partitioning (SURVEY §5 long-context analogue).
Here the TPU-native scale story is explicit (SURVEY §2 parallelism
checklist):

  - **batch axis ("dp")**: independent SSSP roots — whole-fabric RIB
    computation (every node's routes, e.g. the benchmark and the
    any-vantage ctrl API) shards roots across devices; zero communication.
  - **graph axis ("tp"/"cp")**: the node dimension of the ELL mirror is
    sharded across devices; each relaxation step computes new distances
    for the local node shard from the full frontier, then reassembles the
    frontier with jax.lax.all_gather over the 'graph' axis (the halo
    exchange of this domain). This is what lets a 1M+-node LSDB exceed a
    single chip's HBM.

Both axes compose in one jax.sharding.Mesh('batch', 'graph') and ride ICI
when the mesh maps onto a physical slice. Collectives used: all_gather
(frontier), psum-of-bool (convergence vote, folded into the fixed-trip
count here: lax.fori_loop with a diameter bound keeps every device in
lockstep without a host round-trip).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from openr_tpu.ops.csr import INF32

INF = int(INF32)


def make_mesh(n_devices: Optional[int] = None, batch: Optional[int] = None):
    """Factor devices into a ('batch', 'graph') mesh. Prefers a wider
    batch axis (root fan-out is embarrassingly parallel; graph sharding
    pays an all_gather per relaxation step)."""
    import jax

    devs = jax.devices()
    n = n_devices or len(devs)
    devs = devs[:n]
    if batch is None:
        graph = 1
        # give the graph axis a factor of 2 when we have >= 4 devices so
        # both kinds of sharding are exercised
        if n >= 4 and n % 2 == 0:
            graph = 2
        batch = n // graph
    else:
        graph = n // batch
    assert batch * graph == n, (batch, graph, n)
    from jax.sharding import Mesh

    return Mesh(np.array(devs).reshape(batch, graph), ("batch", "graph"))


def _sharded_step_fn(mesh, n_cap: int, n_iters: int):
    """Build the shard_mapped multi-root SSSP + selection step."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    graph_size = mesh.shape["graph"]
    shard_rows = n_cap // graph_size

    def local_step(
        in_nbr,  # [N/g, K]   node-sharded over 'graph'
        in_w,
        in_up,
        node_over,  # [N]     replicated
        roots,  # [R/b]       root-sharded over 'batch'
        ann_node,  # [P, A]   replicated prefix matrix
        ann_valid,
        path_pref,
        source_pref,
        dist_adv,
    ):
        my_shard = jax.lax.axis_index("graph")
        row0 = my_shard * shard_rows

        def one_root(root):
            dist0 = jnp.full((n_cap,), INF, jnp.int32).at[root].set(0)
            usable = in_up & (in_nbr >= 0) & ((in_nbr == root) | ~node_over[in_nbr])

            def body(_, dist):
                # relax local node rows against the full frontier
                nbr_dist = dist[in_nbr]  # [N/g, K] gather from full dist
                cand = jnp.where(
                    usable & (nbr_dist < INF), nbr_dist + in_w, INF
                ).min(axis=1)
                local_new = jnp.minimum(
                    jax.lax.dynamic_slice(dist, (row0,), (shard_rows,)), cand
                )
                # frontier reassembly: the halo exchange of this domain
                return jax.lax.all_gather(
                    local_new, "graph", tiled=True
                )

            dist = jax.lax.fori_loop(0, n_iters, body, dist0)

            # selection for this root over the (replicated) prefix matrix —
            # shared kernel with the single-chip pipeline
            from openr_tpu.decision.tpu_solver import _select_metric_kernel

            metric, _s3, _s4, _idx = _select_metric_kernel(
                dist, node_over, ann_node, ann_valid, path_pref, source_pref, dist_adv
            )
            return dist, metric

        return jax.vmap(one_root)(roots)

    from jax import shard_map

    return jax.jit(
        shard_map(
            local_step,
            mesh=mesh,
            in_specs=(
                P("graph", None),  # in_nbr: node rows sharded
                P("graph", None),
                P("graph", None),
                P(),  # node_over replicated
                P("batch"),  # roots sharded
                P(),  # prefix matrix replicated
                P(),
                P(),
                P(),
                P(),
            ),
            out_specs=(P("batch", None), P("batch", None)),
            check_vma=False,
        )
    )


@functools.lru_cache(maxsize=8)
def _cached_step(mesh, n_cap, n_iters):
    return _sharded_step_fn(mesh, n_cap, n_iters)


def sharded_rib_step(mesh, graph, roots, matrix, n_iters: Optional[int] = None):
    """Run the sharded multi-root pipeline: returns (dist[R, N_cap],
    metric[R, P_cap]) computed across the mesh.

    graph: ops.csr.EllGraph; roots: np int32 array (length must divide the
    batch axis evenly — pad with root 0); matrix: ops.csr.PrefixMatrix.
    n_iters defaults to a safe diameter bound (n_nodes), callers with a
    known topology should pass something tighter.
    """
    n_iters = n_iters or max(graph.n_nodes, 1)
    step = _cached_step(mesh, graph.n_cap, n_iters)
    return step(
        graph.in_nbr,
        graph.in_w,
        graph.in_up,
        graph.node_overloaded,
        roots.astype(np.int32),
        matrix.ann_node,
        matrix.ann_valid,
        matrix.path_pref,
        matrix.source_pref,
        matrix.dist_adv,
    )
