"""PrefixManager actor — owns all prefix advertisement.

Role of the reference's openr/prefix-manager/PrefixManager.{h,cpp} (:81):

  - sources: PrefixEvent queue (plugins / LinkMonitor address
    redistribution / allocator / CLI), originated-from-config prefixes,
    and route redistribution from the Fib's PROGRAMMED delta
    (fibRouteUpdatesQueue — the FIB-ACK path, ref Main.cpp:381-400)
  - per-prefix, per-type ranked prefixMap_: when several sources advertise
    the same prefix, the highest-ranked type wins (ref prefix-type ranking)
  - syncs "prefix:<node>:[<area>]:<prefix>" keys into KvStore via
    kvRequestQueue, throttled (ref syncKvStore)
  - originated prefixes (config): supernode aggregation — advertise the
    covering prefix only while >= minimum_supporting_routes programmed
    subnets exist; install_to_fib emits a static route to Decision via
    staticRouteUpdatesQueue (ref OriginatedPrefix, OpenrConfig.thrift:398)
  - emits initialization event PREFIX_DB_SYNCED
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

from openr_tpu.decision.rib import (
    DecisionRouteUpdate,
    NextHop,
    RibUnicastEntry,
    RouteUpdateType,
)
from openr_tpu.messaging import RQueue, ReplicateQueue
from openr_tpu.runtime.actor import Actor
from openr_tpu.runtime.counters import counters
from openr_tpu.runtime.throttle import AsyncThrottle
from openr_tpu.serde import serialize
from openr_tpu.types import (
    InitializationEvent,
    KeyValueRequest,
    KeyValueRequestType,
    PrefixDatabase,
    PrefixEntry,
    PrefixEvent,
    PrefixEventType,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
    PrefixType,
    parse_prefix,
    prefix_key,
    replace,
)

log = logging.getLogger(__name__)

# higher rank wins when multiple types advertise one prefix
# (ref PrefixManager prefix-type preference)
_TYPE_RANK = {
    PrefixType.LOOPBACK: 9,
    PrefixType.CONFIG: 8,
    PrefixType.VIP: 7,
    PrefixType.BGP: 6,
    PrefixType.DEFAULT: 5,
    PrefixType.PREFIX_ALLOCATOR: 4,
    PrefixType.BREEZE: 3,
    PrefixType.RIB: 1,
}


@dataclass
class OriginatedPrefix:
    """Config-originated covering prefix (ref OpenrConfig.thrift:380-410)."""

    prefix: str
    minimum_supporting_routes: int = 0
    install_to_fib: bool = False
    forwarding_type: int = 0
    tags: tuple[str, ...] = ()
    # advertise with an allocator-assigned prepend label bound to the
    # supporting routes' next-hop group, and program the matching local
    # MPLS route (ref PrependLabelAllocator.h:17-23 LSP stitching)
    allocate_prepend_label: bool = False


@dataclass
class _OriginatedState:
    conf: OriginatedPrefix
    supporting: set[str] = field(default_factory=set)
    advertised: bool = False
    # verdict cached: policies are config-static, and _evaluate_originated
    # re-runs on every FIB delta — without this a denied prefix re-bumps
    # the deny counters forever
    policy_denied: bool = False
    # prepend-label binding: the label and the next-hop set it names
    prepend_label: Optional[int] = None
    label_nh_set: frozenset = frozenset()


class PrefixManager(Actor):
    """ref PrefixManager.h:81."""

    def __init__(
        self,
        node_name: str,
        areas: list[str],
        prefix_updates_queue: RQueue,
        fib_route_updates_queue: Optional[RQueue],
        kv_request_queue: ReplicateQueue,
        static_routes_queue: Optional[ReplicateQueue] = None,
        kvstore_updates_queue: Optional[ReplicateQueue] = None,
        originated_prefixes: Optional[list[OriginatedPrefix]] = None,
        sync_throttle_s: float = 0.005,
        policy_manager=None,
        origination_policy: str = "",
        area_policies: Optional[dict[str, str]] = None,
    ):
        super().__init__(f"prefix-manager:{node_name}")
        self.node_name = node_name
        self.areas = areas
        # origination-policy hook (ref PolicyManager wiring,
        # PrefixManager.cpp policy application on advertisement ingress)
        self.policy_manager = policy_manager
        self.origination_policy = origination_policy
        # per-destination-area import policies (ref areaToPolicy_,
        # PrefixManager.cpp:76 + :506 — applied per area at key
        # advertisement): area_id -> policy name
        self.area_policies = dict(area_policies or {})
        self._prefix_updates = prefix_updates_queue
        self._fib_updates = fib_route_updates_queue
        self._kv_request_q = kv_request_queue
        self._static_q = static_routes_queue
        self._kvstore_updates_q = kvstore_updates_queue
        # prefix -> {type -> PrefixEntry}
        self.prefix_map: dict[str, dict[PrefixType, PrefixEntry]] = {}
        # (prefix, type) -> restricted destination areas; absent = all
        self._dest_areas: dict[tuple[str, PrefixType], tuple[str, ...]] = {}
        self.originated: dict[str, _OriginatedState] = {
        }
        for op in originated_prefixes or []:
            self.originated[op.prefix] = _OriginatedState(conf=op)
        # what we currently advertise in kvstore, post-area-policy:
        # prefix -> {area -> PrefixEntry as advertised there}
        self._advertised: dict[str, dict[str, PrefixEntry]] = {}
        # (prefix, area) -> (pre-policy entry, post-policy entry|None):
        # the throttled sync re-walks the whole desired set, so policy
        # evaluation (and its hit counters) must only run when the
        # pre-policy entry for that area actually changed
        self._area_policy_memo: dict[tuple[str, str], tuple] = {}
        # prefixes currently re-advertised across areas as RIB transit
        self._redistributed: set[str] = set()
        self._sync_throttle: Optional[AsyncThrottle] = None
        self._sync_throttle_s = sync_throttle_s
        self._db_synced_signalled = False
        # prepend labels (ref PrependLabelAllocator): created on first
        # use; bindings live on _OriginatedState
        self._label_allocator = None
        # programmed-route next hops, for label next-hop groups — only
        # tracked when some originated prefix allocates labels (100k
        # persistent frozensets otherwise, all dead weight)
        self._track_nexthops = any(
            o.conf.allocate_prepend_label for o in self.originated.values()
        )
        self._route_nexthops: dict[str, frozenset] = {}

    async def on_start(self) -> None:
        self._sync_throttle = AsyncThrottle(
            self._sync_throttle_s, self.sync_kvstore
        )
        self.add_task(self._prefix_loop(), name=f"{self.name}.prefixes")
        if self._fib_updates is not None:
            self.add_task(self._fib_loop(), name=f"{self.name}.fib-acks")
        # originated prefixes with no support requirement advertise at once
        self._evaluate_originated()
        self._sync_throttled()

    # -- prefix event sources (ref PrefixEvent LsdbTypes.h:275) ------------

    async def _prefix_loop(self) -> None:
        while True:
            ev: PrefixEvent = await self._prefix_updates.get()
            self.process_prefix_event(ev)

    def process_prefix_event(self, ev: PrefixEvent) -> None:
        if ev.event_type == PrefixEventType.ADD_PREFIXES:
            self.advertise_prefixes(ev.prefixes, ev.type, ev.dest_areas)
        elif ev.event_type == PrefixEventType.WITHDRAW_PREFIXES:
            self.withdraw_prefixes(ev.prefixes, ev.type)
        elif ev.event_type == PrefixEventType.WITHDRAW_PREFIXES_BY_TYPE:
            self.withdraw_prefixes_by_type(ev.type)
        elif ev.event_type == PrefixEventType.SYNC_PREFIXES_BY_TYPE:
            self.sync_prefixes_by_type(ev.prefixes, ev.type)

    def _apply_origination_policy(
        self, entry: PrefixEntry
    ) -> Optional[PrefixEntry]:
        """None = denied by policy (the entry is not advertised)."""
        if self.policy_manager is None or not self.origination_policy:
            return entry
        out = self.policy_manager.apply(self.origination_policy, entry)
        if out is None:
            counters.increment("prefix_manager.policy_denied")
        return out

    def _admit(
        self, prefixes: list[PrefixEntry], ptype: PrefixType
    ) -> list[PrefixEntry]:
        """Type-stamp + origination policy, applied exactly once per
        entry; denied entries drop out here."""
        out = []
        for entry in prefixes:
            if entry.type != ptype:
                entry = replace(entry, type=ptype)
            entry = self._apply_origination_policy(entry)
            if entry is not None:
                out.append(entry)
        return out

    def _store_entries(
        self, admitted: list[PrefixEntry], dest_areas: tuple[str, ...]
    ) -> None:
        for entry in admitted:
            self.prefix_map.setdefault(entry.prefix, {})[entry.type] = entry
            if dest_areas:
                self._dest_areas[(entry.prefix, entry.type)] = tuple(dest_areas)
            else:
                self._dest_areas.pop((entry.prefix, entry.type), None)

    def advertise_prefixes(
        self,
        prefixes: list[PrefixEntry],
        ptype: PrefixType,
        dest_areas: tuple[str, ...] = (),
    ) -> None:
        admitted = self._admit(prefixes, ptype)
        self._store_entries(admitted, dest_areas)
        counters.increment("prefix_manager.advertised", len(admitted))
        self._sync_throttled()

    def withdraw_prefixes(
        self, prefixes: list[PrefixEntry], ptype: PrefixType
    ) -> None:
        for entry in prefixes:
            types = self.prefix_map.get(entry.prefix)
            if types is not None:
                types.pop(ptype, None)
                if not types:
                    del self.prefix_map[entry.prefix]
            self._dest_areas.pop((entry.prefix, ptype), None)
        counters.increment("prefix_manager.withdrawn", len(prefixes))
        self._sync_throttled()

    def withdraw_prefixes_by_type(self, ptype: PrefixType) -> None:
        for prefix in list(self.prefix_map):
            types = self.prefix_map[prefix]
            types.pop(ptype, None)
            self._dest_areas.pop((prefix, ptype), None)
            if not types:
                del self.prefix_map[prefix]
        self._sync_throttled()

    def sync_prefixes_by_type(
        self, prefixes: list[PrefixEntry], ptype: PrefixType
    ) -> None:
        """Replace the full set for a type (ref syncPrefixesByType).
        Policy runs BEFORE the keep-set: an entry the policy now denies
        must be withdrawn, not left at its stale previously-accepted
        version."""
        admitted = self._admit(prefixes, ptype)
        keep = {p.prefix for p in admitted}
        for prefix in list(self.prefix_map):
            types = self.prefix_map[prefix]
            if ptype in types and prefix not in keep:
                types.pop(ptype)
                if not types:
                    del self.prefix_map[prefix]
        self._store_entries(admitted, ())
        counters.increment("prefix_manager.advertised", len(admitted))
        self._sync_throttled()

    # -- FIB-ACK redistribution + supernode aggregation --------------------

    async def _fib_loop(self) -> None:
        while True:
            item = await self._fib_updates.get()
            if isinstance(item, InitializationEvent):
                continue
            self._process_programmed_routes(item)

    def _process_programmed_routes(self, upd: DecisionRouteUpdate) -> None:
        """Track programmed routes as supporting evidence for originated
        covering prefixes (ref aggregation, minimum_supporting_routes),
        and — with multiple areas configured — redistribute them into the
        areas they did not come from (ref
        redistributePrefixesAcrossAreas, PrefixManager.cpp:1662-1765)."""
        if len(self.areas) > 1:
            self._redistribute_across_areas(upd)
        changed = False
        # the per-entry walk forces route values out of the update map —
        # a FIB-ACK carrying a lazy columnar table materializes entries
        # here. Skip it outright when nothing consumes them (no segment
        # labels to track, no originated prefixes to support)
        if self._track_nexthops or self.originated:
            for prefix, entry in upd.unicast_routes_to_update.items():
                if self._track_nexthops:
                    nhs = frozenset(
                        nh.address for nh in entry.nexthops if nh.address
                    )
                    if self._route_nexthops.get(prefix) != nhs:
                        self._route_nexthops[prefix] = nhs
                        changed = True  # next-hop group may move the label
                for ostate in self.originated.values():
                    if self._supports(prefix, ostate.conf.prefix):
                        if prefix not in ostate.supporting:
                            ostate.supporting.add(prefix)
                            changed = True
        for prefix in upd.unicast_routes_to_delete:
            self._route_nexthops.pop(prefix, None)
            for ostate in self.originated.values():
                if prefix in ostate.supporting:
                    ostate.supporting.discard(prefix)
                    changed = True
        if changed:
            self._evaluate_originated()
            self._sync_throttled()

    def _redistribute_across_areas(self, upd: DecisionRouteUpdate) -> None:
        """Re-advertise programmed routes into the areas they did NOT
        come from, as transit (ref PrefixManager.cpp:1662-1765):
        provenance appends to area_stack (the key-sync loop guard skips
        destination areas already on the stack), distance bumps by one,
        the type normalizes to RIB (lowest rank, so a redistributed copy
        never beats an original announcement), and non-transitive
        attributes reset (ref resetNonTransitiveAttrs)."""
        by_dst: dict[tuple[str, ...], list[PrefixEntry]] = {}
        no_longer: list[str] = []
        for prefix, route in upd.unicast_routes_to_update.items():
            best = route.best_prefix_entry
            if best is None or prefix in self.originated:
                if best is None and prefix in self._redistributed:
                    no_longer.append(prefix)
                continue
            src_areas = {nh.area for nh in route.nexthops if nh.area}
            dst = tuple(a for a in self.areas if a not in src_areas)
            if not dst:
                # an update that stops qualifying (now reachable via
                # every area) must retract its earlier re-advertisement,
                # not leave a stale transit claim
                if prefix in self._redistributed:
                    no_longer.append(prefix)
                continue
            entry = replace(
                best,
                prefix=prefix,
                type=PrefixType.RIB,
                area_stack=tuple(best.area_stack)
                + (route.best_node_area[1],),
                metrics=replace(
                    best.metrics, distance=best.metrics.distance + 1
                ),
                forwarding_type=PrefixForwardingType.IP,
                forwarding_algorithm=PrefixForwardingAlgorithm.SP_ECMP,
                min_nexthop=None,
                prepend_label=None,
                weight=None,
            )
            by_dst.setdefault(dst, []).append(entry)
        if upd.type == RouteUpdateType.FULL_SYNC:
            # a restart's full sync replaces the whole programmed set:
            # withdraw redistributed prefixes the new RIB no longer has
            keep = set(upd.unicast_routes_to_update)
            stale = [
                p for p in self._redistributed if p not in keep
            ]
            if stale:
                self.withdraw_prefixes(
                    [PrefixEntry(prefix=p) for p in stale], PrefixType.RIB
                )
                self._redistributed.difference_update(stale)
        for dst, entries in by_dst.items():
            self._redistributed.update(e.prefix for e in entries)
            self.advertise_prefixes(entries, PrefixType.RIB, dst)
        deleted = no_longer + [
            p
            for p in upd.unicast_routes_to_delete
            if p in self._redistributed and p not in self.originated
        ]
        if deleted:
            self._redistributed.difference_update(deleted)
            self.withdraw_prefixes(
                [PrefixEntry(prefix=p) for p in deleted], PrefixType.RIB
            )

    @staticmethod
    def _supports(route_prefix: str, covering: str) -> bool:
        try:
            route_net = parse_prefix(route_prefix)
            cover_net = parse_prefix(covering)
        except ValueError:
            return False
        return (
            route_net.version == cover_net.version
            and route_net != cover_net
            and route_net.subnet_of(cover_net)
        )

    def _ensure_label_allocator(self):
        if self._label_allocator is None:
            from openr_tpu.allocators.prepend_label import (
                PrependLabelAllocator,
            )

            self._label_allocator = PrependLabelAllocator()
        return self._label_allocator

    def _supporting_nexthops(self, ostate: _OriginatedState) -> frozenset:
        """The next-hop group a prepend label names: the union of the
        supporting routes' programmed next hops."""
        out: set = set()
        for prefix in ostate.supporting:
            out |= self._route_nexthops.get(prefix, frozenset())
        return frozenset(out)

    def _bind_prepend_label(self, ostate: _OriginatedState) -> Optional[int]:
        """(Re)bind the prefix's prepend label to its current next-hop
        group; programs/updates the local MPLS route through the static
        routes queue (ref PrependLabelAllocator.h:17-23)."""
        from openr_tpu.decision.rib import RibMplsEntry

        alloc = self._ensure_label_allocator()
        nh_set = self._supporting_nexthops(ostate)
        if nh_set == ostate.label_nh_set and ostate.prepend_label is not None:
            return ostate.prepend_label
        upd = DecisionRouteUpdate(type=RouteUpdateType.INCREMENTAL)
        label, _new = alloc.increment_ref_count(nh_set)
        if ostate.label_nh_set:
            freed = alloc.decrement_ref_count(ostate.label_nh_set)
            if freed is not None:
                upd.mpls_routes_to_delete.append(freed)
        ostate.label_nh_set = nh_set
        ostate.prepend_label = label
        if label is not None:
            upd.mpls_routes_to_update[label] = RibMplsEntry(
                label=label,
                nexthops=frozenset(
                    NextHop(address=a) for a in sorted(nh_set)
                ),
            )
        if self._static_q is not None and not upd.empty():
            self._static_q.push(upd)
        return label

    def _release_prepend_label(self, ostate: _OriginatedState) -> None:
        if ostate.prepend_label is None and not ostate.label_nh_set:
            return
        alloc = self._ensure_label_allocator()
        freed = alloc.decrement_ref_count(ostate.label_nh_set)
        ostate.prepend_label = None
        ostate.label_nh_set = frozenset()
        if freed is not None and self._static_q is not None:
            self._static_q.push(
                DecisionRouteUpdate(
                    type=RouteUpdateType.INCREMENTAL,
                    mpls_routes_to_delete=[freed],
                )
            )

    def _evaluate_originated(self) -> None:
        for ostate in self.originated.values():
            conf = ostate.conf
            should = len(ostate.supporting) >= conf.minimum_supporting_routes
            if should and ostate.advertised and conf.allocate_prepend_label:
                # supporting next-hop group may have moved: rebind, and
                # re-advertise if the label changed
                old = ostate.prepend_label
                label = self._bind_prepend_label(ostate)
                if label != old:
                    types = self.prefix_map.get(conf.prefix, {})
                    cur = types.get(PrefixType.CONFIG)
                    if cur is not None:
                        types[PrefixType.CONFIG] = replace(
                            cur, prepend_label=label
                        )
            if should and not ostate.advertised:
                if ostate.policy_denied:
                    continue
                entry = self._apply_origination_policy(
                    PrefixEntry(
                        prefix=conf.prefix,
                        type=PrefixType.CONFIG,
                        tags=conf.tags,
                    )
                )
                if entry is None:
                    ostate.policy_denied = True
                    continue  # policy-denied: stays unadvertised
                if conf.allocate_prepend_label:
                    entry = replace(
                        entry,
                        prepend_label=self._bind_prepend_label(ostate),
                    )
                ostate.advertised = True
                self.prefix_map.setdefault(conf.prefix, {})[
                    PrefixType.CONFIG
                ] = entry
                if conf.install_to_fib and self._static_q is not None:
                    self._static_q.push(
                        DecisionRouteUpdate(
                            unicast_routes_to_update={
                                conf.prefix: RibUnicastEntry(
                                    prefix=conf.prefix,
                                    nexthops=frozenset(
                                        {NextHop(address="::", if_name="lo")}
                                    ),
                                    best_prefix_entry=entry,
                                )
                            }
                        )
                    )
                counters.increment("prefix_manager.originated_advertised")
            elif not should and ostate.advertised:
                ostate.advertised = False
                if conf.allocate_prepend_label:
                    self._release_prepend_label(ostate)
                types = self.prefix_map.get(conf.prefix)
                if types is not None:
                    types.pop(PrefixType.CONFIG, None)
                    if not types:
                        del self.prefix_map[conf.prefix]
                if conf.install_to_fib and self._static_q is not None:
                    self._static_q.push(
                        DecisionRouteUpdate(
                            unicast_routes_to_delete=[conf.prefix]
                        )
                    )
                counters.increment("prefix_manager.originated_withdrawn")

    # -- KvStore sync (ref syncKvStore) ------------------------------------

    def _sync_throttled(self) -> None:
        if self._sync_throttle is not None:
            self._sync_throttle()

    def best_entries(self) -> dict[str, PrefixEntry]:
        """Per prefix, the entry of the highest-ranked type."""
        out = {}
        for prefix, types in self.prefix_map.items():
            best_type = max(types, key=lambda t: _TYPE_RANK.get(t, 0))
            out[prefix] = types[best_type]
        return out

    def _areas_for(self, prefix: str, entry: PrefixEntry) -> tuple[str, ...]:
        restricted = self._dest_areas.get((prefix, entry.type))
        areas = restricted if restricted else tuple(self.areas)
        # area_stack loop guard (ref addKvStoreKeyHelper,
        # PrefixManager.cpp:495-499): never advertise a prefix back into
        # an area it already transited; local originations have an empty
        # stack so this is a no-op for them
        if entry.area_stack:
            areas = tuple(a for a in areas if a not in entry.area_stack)
        return areas

    def _entry_for_area(
        self, prefix: str, entry: PrefixEntry, area: str
    ) -> Optional[PrefixEntry]:
        """Run the destination area's import policy (ref areaToPolicy_
        application, PrefixManager.cpp:506-533): transformed entry, or
        None when the policy rejects the advertisement into this area.
        Memoized per (prefix, area) on the pre-policy entry, so steady
        syncs don't re-match regexes or skew hit counters."""
        name = self.area_policies.get(area)
        if not name or self.policy_manager is None:
            return entry
        policy = self.policy_manager.policies.get(name)
        memo = self._area_policy_memo.get((prefix, area))
        # the policy OBJECT is part of the key: replacing a policy at
        # runtime must re-evaluate even for unchanged entries
        if memo is not None and memo[0] == entry and memo[1] is policy:
            return memo[2]
        out = self.policy_manager.apply(name, entry)
        self._area_policy_memo[(prefix, area)] = (entry, policy, out)
        return out

    def sync_kvstore(self) -> None:
        desired = self.best_entries()
        # desired advertisement set per (prefix, area), post-area-policy
        new_advertised: dict[str, dict[str, PrefixEntry]] = {}
        for prefix, entry in desired.items():
            per_area: dict[str, PrefixEntry] = {}
            for area in self._areas_for(prefix, entry):
                out = self._entry_for_area(prefix, entry, area)
                if out is not None:
                    per_area[area] = out
            if per_area:
                new_advertised[prefix] = per_area
        # drop memo entries for prefixes no longer advertised at all
        self._area_policy_memo = {
            k: v for k, v in self._area_policy_memo.items()
            if k[0] in desired
        }
        for prefix, per_area in new_advertised.items():
            old = self._advertised.get(prefix)
            for area, entry in per_area.items():
                if old is not None and old.get(area) == entry:
                    continue
                self._kv_request_q.push(
                    KeyValueRequest(
                        request_type=KeyValueRequestType.PERSIST,
                        area=area,
                        key=prefix_key(self.node_name, area, prefix),
                        value=serialize(
                            PrefixDatabase(
                                this_node_name=self.node_name,
                                prefix_entries=(entry,),
                                area=area,
                            )
                        ),
                    )
                )
        # withdrawals: one-shot delete_prefix tombstone (SET, not PERSIST —
        # it must flood once and age out, not be defended); also tombstone
        # areas a prefix was re-scoped away from (or newly policy-denied)
        for prefix, old_per_area in self._advertised.items():
            now = new_advertised.get(prefix, {})
            gone_areas = tuple(a for a in old_per_area if a not in now)
            for area in gone_areas:
                self._kv_request_q.push(
                    KeyValueRequest(
                        request_type=KeyValueRequestType.SET,
                        area=area,
                        key=prefix_key(self.node_name, area, prefix),
                        value=serialize(
                            PrefixDatabase(
                                this_node_name=self.node_name,
                                prefix_entries=(PrefixEntry(prefix=prefix),),
                                area=area,
                                delete_prefix=True,
                            )
                        ),
                        set_ttl=2_000,  # tombstone ages out quickly
                    )
                )
        self._advertised = new_advertised
        counters.increment("prefix_manager.kvstore_syncs")
        if not self._db_synced_signalled:
            self._db_synced_signalled = True
            if self._kvstore_updates_q is not None:
                self._kvstore_updates_q.push(
                    InitializationEvent.PREFIX_DB_SYNCED
                )

    # -- module API (ref PrefixManager.h:121-135) --------------------------

    async def get_prefixes(self) -> dict[str, PrefixEntry]:
        return self.best_entries()

    async def get_advertised_routes(self) -> dict[str, PrefixEntry]:
        # per-area policies can transform entries per destination; the
        # un-scoped view reports one representative advertisement
        return {
            p: next(iter(per_area.values()))
            for p, per_area in self._advertised.items()
        }

    async def get_area_advertised_routes(
        self, area: str
    ) -> dict[str, PrefixEntry]:
        """What this node advertises INTO one area (ref
        getAreaAdvertisedRoutes, OpenrCtrl.thrift:~330) — honors
        destination-area restrictions AND that area's import policy."""
        return {
            p: per_area[area]
            for p, per_area in self._advertised.items()
            if area in per_area
        }
