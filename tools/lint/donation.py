"""Buffer-donation checker (`donated-read`).

`donate_argnums` hands an input buffer's HBM to XLA for in-place reuse
— after dispatch the Python handle is deleted/invalid, and touching it
again raises (GPU/TPU) or silently reads stale memory depending on
backend and timing. The delta-sync path (`_sync_area` ->
`_diff_scatter` -> `_scatter_counted` -> `_scatter_jit`/
`_mc_scatter_jit`) donates the resident device array on every scatter,
so the contract is: a donated expression must not be READ on any path
after the donating call. The safe idiom is the same-statement rebind —

    ad.d_shift_w = self._diff_scatter(ad.d_shift_w, ...)

— where the stale handle is overwritten by the result in the very
statement that donates it.

Detection:

1. Index donating callables:
   - factories whose body jits with `donate_argnums=(...)` (including
     the `{"donate_argnums": ...}` kwargs-dict form) — a call of the
     factory's RESULT donates those positions;
   - names bound directly to `jax.jit(f, donate_argnums=...)`;
   - wrappers, to a fixpoint: a def that forwards one of its own
     parameters into a donated position of a known donating callable
     donates that parameter position to ITS callers (`self._...`
     method calls shift positions by one for the receiver).
2. Within each def, a statement that makes a donating call marks the
   donated argument expressions dead from the end of that statement —
   UNLESS the statement assigns the result back to the identical
   expression (the rebind idiom), or is a `return` (control flow
   leaves, nothing downstream on that path can read it).
3. Any later load of a dead expression in the same def is flagged.
"""

from __future__ import annotations

import ast

from tools.lint.core import Finding, Project
from tools.lint.purity import _is_traced_file, _terminal_name

CODE = "donated-read"


def _donated_positions(call: ast.Call) -> set[int] | None:
    """donate_argnums positions declared on a jit call, else None."""
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return _const_positions(kw.value)
    return None


def _const_positions(node: ast.AST) -> set[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(
                elt.value, int
            ):
                out.add(elt.value)
        return out
    return set()


def _factory_donations(fn: ast.AST) -> set[int]:
    """Donated positions of the callable a factory returns: union of
    every `donate_argnums` its body declares, in keyword or
    kwargs-dict form (a conditional `{"donate_argnums": (0,)} if
    donate else {}` still donates on SOME path, which is what the
    read-after rule cares about)."""
    positions: set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            pos = _donated_positions(node)
            if pos:
                positions |= pos
        elif isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if (
                    isinstance(k, ast.Constant)
                    and k.value == "donate_argnums"
                ):
                    positions |= _const_positions(v)
    return positions


def _param_names(fn) -> list[str]:
    a = fn.args
    return [arg.arg for arg in (*a.posonlyargs, *a.args, *a.kwonlyargs)]


class _Index:
    """Project-wide donating-callable index, name-granular (the repo
    doesn't reuse factory/wrapper names across modules)."""

    def __init__(self, project: Project):
        # factory name -> donated positions of the returned callable
        self.factories: dict[str, set[int]] = {}
        # callable/wrapper name -> donated CALL-SITE arg positions
        self.wrappers: dict[str, set[int]] = {}
        self.defs: list[tuple] = []  # (SourceFile, def node)
        for sf in project.files:
            if not _is_traced_file(sf.rel):
                continue
            for node in ast.walk(sf.tree):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    self.defs.append((sf, node))
                    pos = _factory_donations(node)
                    if pos:
                        self.factories[node.name] = pos
                elif isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    # x = jax.jit(f, donate_argnums=(0,))
                    pos = _donated_positions(node.value)
                    if pos and len(node.targets) == 1 and isinstance(
                        node.targets[0], ast.Name
                    ):
                        self.wrappers[node.targets[0].id] = pos
        self._propagate()

    def donated_args(self, call: ast.Call) -> list[ast.AST]:
        """Argument expressions a call donates, or []."""
        # factory double-call: Factory(...)(buf, ...)
        if isinstance(call.func, ast.Call):
            fname = _terminal_name(call.func.func)
            pos = self.factories.get(fname or "")
            if pos:
                return [
                    call.args[p] for p in pos if p < len(call.args)
                ]
            return []
        name = _terminal_name(call.func)
        pos = self.wrappers.get(name or "")
        if pos:
            return [call.args[p] for p in pos if p < len(call.args)]
        return []

    def _propagate(self) -> None:
        changed = True
        while changed:
            changed = False
            for _sf, fn in self.defs:
                params = _param_names(fn)
                is_method = bool(params) and params[0] in ("self", "cls")
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    for arg in self.donated_args(node):
                        if not isinstance(arg, ast.Name):
                            continue
                        if arg.id not in params:
                            continue
                        p = params.index(arg.id)
                        if is_method:
                            p -= 1  # callers pass via `self.f(...)`
                        if p < 0:
                            continue
                        got = self.wrappers.setdefault(fn.name, set())
                        if p not in got:
                            got.add(p)
                            changed = True


def _parents(tree: ast.AST) -> dict[ast.AST, ast.AST]:
    par: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            par[child] = node
    return par


def _stmt_of(node: ast.AST, parents: dict) -> ast.stmt | None:
    while node is not None and not isinstance(node, ast.stmt):
        node = parents.get(node)
    return node


def run(project: Project) -> list[Finding]:
    index = _Index(project)
    findings: list[Finding] = []
    for sf, fn in index.defs:
        parents = _parents(fn)
        # (unparsed donated expr, dead-after line)
        dead: list[tuple[str, int]] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            args = index.donated_args(node)
            if not args:
                continue
            stmt = _stmt_of(node, parents)
            if stmt is None or isinstance(stmt, ast.Return):
                continue
            rebinds: set[str] = set()
            if isinstance(stmt, ast.Assign):
                rebinds = {ast.unparse(t) for t in stmt.targets}
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                rebinds = {ast.unparse(stmt.target)}
            for arg in args:
                if not isinstance(arg, (ast.Name, ast.Attribute)):
                    continue
                expr = ast.unparse(arg)
                if expr in rebinds:
                    continue
                dead.append((expr, stmt.end_lineno or stmt.lineno))
        if not dead:
            continue
        for node in ast.walk(fn):
            if not (
                isinstance(node, (ast.Name, ast.Attribute))
                and isinstance(node.ctx, ast.Load)
            ):
                continue
            expr = ast.unparse(node)
            for dexpr, after in dead:
                if expr == dexpr and node.lineno > after:
                    findings.append(Finding(
                        sf.rel, node.lineno, CODE,
                        sf.scope_at(node.lineno), dexpr,
                        f"`{dexpr}` is read after being donated at "
                        f"line {after} — the donated buffer's handle "
                        f"is invalid after dispatch; rebind the "
                        f"result onto the same expression in the "
                        f"donating statement, or drop the later read",
                    ))
                    break
    seen: set[tuple] = set()
    out = []
    for fd in findings:
        k = (fd.path, fd.line, fd.code, fd.detail)
        if k not in seen:
            seen.add(k)
            out.append(fd)
    return out
