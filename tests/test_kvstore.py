"""KvStore engine + actor tests.

Mirrors the reference's test strategy (SURVEY §4): merge-matrix unit tests
(ref openr/kvstore/tests/KvStoreUtilTest.cpp), TTL tests (KvStoreTtlTest),
multi-instance sync/flood over real TCP via the in-process wrapper
(ref KvStoreWrapper + KvStoreTest.cpp, KvStoreThriftTest), and
self-originated key defense (KvStoreSelfOriginatedKeyTest).
"""

import asyncio

from openr_tpu.kvstore.engine import (
    KvStoreFilters,
    MergeStats,
    TtlCountdownQueue,
    compare_values,
    dump_difference,
    merge_key_values,
)
from openr_tpu.kvstore.wrapper import (
    KvStoreWrapper,
    wait_converged,
    wait_until,
)
from openr_tpu.types import (
    FilterOperator,
    KvStorePeerState,
    Publication,
    Value,
)
from tests.conftest import run_async


def v(
    version=1, originator="node1", value=b"x", ttl=-1, ttl_version=0, hash=None
):
    return Value(
        version=version,
        originator_id=originator,
        value=value,
        ttl_ms=ttl,
        ttl_version=ttl_version,
        hash=hash,
    )


# ---------------------------------------------------------------------------
# merge matrix (ref KvStoreUtilTest.cpp)
# ---------------------------------------------------------------------------

class TestMergeKeyValues:
    def test_new_key_added(self):
        kv = {}
        updates = merge_key_values(kv, {"k": v()})
        assert set(updates) == {"k"}
        assert kv["k"].value == b"x"
        assert kv["k"].hash is not None  # hash filled on merge

    def test_higher_version_wins(self):
        kv = {"k": v(version=1, value=b"old")}
        updates = merge_key_values(kv, {"k": v(version=2, value=b"new")})
        assert set(updates) == {"k"}
        assert kv["k"].value == b"new"

    def test_lower_version_rejected(self):
        kv = {"k": v(version=5, value=b"mine")}
        st = MergeStats()
        updates = merge_key_values(kv, {"k": v(version=4, value=b"other")}, stats=st)
        assert not updates
        assert st.old_version == 1
        assert kv["k"].value == b"mine"

    def test_version_tie_higher_originator_wins(self):
        kv = {"k": v(originator="aaa", value=b"a")}
        updates = merge_key_values(kv, {"k": v(originator="bbb", value=b"b")})
        assert set(updates) == {"k"}
        assert kv["k"].originator_id == "bbb"

    def test_version_tie_lower_originator_rejected(self):
        kv = {"k": v(originator="bbb", value=b"b")}
        st = MergeStats()
        updates = merge_key_values(
            kv, {"k": v(originator="aaa", value=b"a")}, stats=st
        )
        assert not updates
        assert st.no_need_to_update == 1

    def test_full_tie_higher_value_wins(self):
        kv = {"k": v(value=b"aaa")}
        updates = merge_key_values(kv, {"k": v(value=b"bbb")})
        assert set(updates) == {"k"}
        assert kv["k"].value == b"bbb"

    def test_identical_no_update(self):
        kv = {"k": v()}
        st = MergeStats()
        updates = merge_key_values(kv, {"k": v()}, stats=st)
        assert not updates
        assert st.no_need_to_update == 1

    def test_ttl_refresh_same_value(self):
        kv = {"k": v(ttl=1000, ttl_version=0)}
        updates = merge_key_values(kv, {"k": v(ttl=2000, ttl_version=1)})
        assert set(updates) == {"k"}
        assert kv["k"].ttl_version == 1
        assert kv["k"].ttl_ms == 2000

    def test_hash_only_ttl_refresh(self):
        kv = {"k": v(ttl=1000)}
        refresh = v(value=None, ttl=1000, ttl_version=3)
        updates = merge_key_values(kv, {"k": refresh})
        assert set(updates) == {"k"}
        assert kv["k"].ttl_version == 3
        assert kv["k"].value == b"x"  # data untouched

    def test_hash_only_no_local_key_ignored(self):
        kv = {}
        updates = merge_key_values(kv, {"k": v(value=None)})
        assert not updates and not kv

    def test_invalid_ttl_rejected(self):
        kv = {}
        st = MergeStats()
        updates = merge_key_values(kv, {"k": v(ttl=0)}, stats=st)
        assert not updates
        assert st.invalid_ttl == 1

    def test_version_zero_rejected(self):
        kv = {}
        updates = merge_key_values(kv, {"k": v(version=0)})
        assert not updates

    # -- origin stamps (ISSUE 11: cross-node trace stitching) --------------
    # The origin stamp (origin_node/origin_event_id/origin_ts_ms) rides
    # the winning value verbatim and is EXCLUDED from the merge hash, so
    # stamps can never flip a merge verdict.

    def sv(self, stamp="node1:17", node="node1", ts=1111.0, **kw):
        val = v(**kw)
        val.origin_node = node if stamp else None
        val.origin_event_id = stamp or None
        val.origin_ts_ms = ts if stamp else None
        return val

    def test_stamp_rides_winning_higher_version(self):
        kv = {"k": v(version=1, value=b"old")}
        updates = merge_key_values(
            kv, {"k": self.sv(version=2, value=b"new")}
        )
        assert set(updates) == {"k"}
        assert kv["k"].origin_event_id == "node1:17"
        assert kv["k"].origin_node == "node1"
        assert kv["k"].origin_ts_ms == 1111.0

    def test_losing_stamp_does_not_survive(self):
        # the local stamped value loses to a higher-version unstamped
        # one: the WINNER's (absent) stamp is what remains
        kv = {"k": self.sv(version=1, value=b"old")}
        updates = merge_key_values(kv, {"k": v(version=2, value=b"new")})
        assert set(updates) == {"k"}
        assert kv["k"].origin_event_id is None

    def test_ttl_only_refresh_preserves_stamp(self):
        kv = {"k": self.sv(ttl=1000)}
        refresh = v(value=None, ttl=2000, ttl_version=3)
        updates = merge_key_values(kv, {"k": refresh})
        assert set(updates) == {"k"}
        assert kv["k"].ttl_version == 3
        assert kv["k"].origin_event_id == "node1:17"
        assert kv["k"].origin_ts_ms == 1111.0

    def test_stamp_never_flips_originator_tiebreak(self):
        # same version: originator tiebreak decides, regardless of which
        # side carries a stamp or what it says
        kv = {"k": self.sv(originator="bbb", value=b"b")}
        st = MergeStats()
        updates = merge_key_values(
            kv,
            {"k": self.sv(stamp="node9:99", node="node9",
                          originator="aaa", value=b"a")},
            stats=st,
        )
        assert not updates
        assert st.no_need_to_update == 1
        assert kv["k"].origin_event_id == "node1:17"

    def test_stamp_difference_alone_is_no_update(self):
        # identical (version, originator, value): a differing stamp must
        # not look like new data — stamps are hash-excluded
        kv = {"k": self.sv()}
        st = MergeStats()
        updates = merge_key_values(
            kv, {"k": self.sv(stamp="node2:5", node="node2", ts=9.0)},
            stats=st,
        )
        assert not updates
        assert st.no_need_to_update == 1
        assert kv["k"].origin_event_id == "node1:17"

    def test_stamp_excluded_from_hash(self):
        a, b = self.sv(), self.sv(stamp="other:1", node="other", ts=5.0)
        assert a.hash == b.hash

    def test_stamp_survives_serde_roundtrip(self):
        from openr_tpu.serde import from_plain, to_plain

        val = self.sv()
        back = from_plain(to_plain(val), Value)
        assert back.origin_node == "node1"
        assert back.origin_event_id == "node1:17"
        assert back.origin_ts_ms == 1111.0

    def test_filters_respected(self):
        kv = {}
        filters = KvStoreFilters(key_prefixes=("adj:",))
        st = MergeStats()
        updates = merge_key_values(
            kv, {"prefix:n1": v(), "adj:n1": v()}, filters=filters, stats=st
        )
        assert set(updates) == {"adj:n1"}
        assert st.no_matched_key == 1

    def test_filters_and_operator(self):
        filters = KvStoreFilters(
            key_prefixes=("adj:",),
            originator_ids=frozenset({"node1"}),
            operator=FilterOperator.AND,
        )
        assert filters.key_match("adj:x", v(originator="node1"))
        assert not filters.key_match("adj:x", v(originator="node2"))
        assert not filters.key_match("prefix:x", v(originator="node1"))


class TestCompareValues:
    def test_version_dominates(self):
        assert compare_values(v(version=2), v(version=1)) == 1
        assert compare_values(v(version=1), v(version=2)) == -1

    def test_originator_breaks_tie(self):
        assert compare_values(v(originator="b"), v(originator="a")) == 1

    def test_equal_hash_compares_ttl_version(self):
        a, b = v(ttl_version=1), v(ttl_version=0)
        assert compare_values(a, b) == 1
        assert compare_values(b, a) == -1
        assert compare_values(v(), v()) == 0

    def test_missing_value_unknown(self):
        a = v()
        b = Value(version=1, originator_id="node1", value=None, hash=123)
        assert compare_values(a, b) == -2


class TestDumpDifference:
    def test_disjoint_keys(self):
        mine = {"a": v()}
        theirs = {"b": v()}
        pub = dump_difference("0", mine, theirs)
        assert set(pub.key_vals) == {"a"}
        assert pub.to_be_updated_keys == ["b"]

    def test_mine_better(self):
        mine = {"k": v(version=3)}
        theirs = {"k": v(version=2)}
        pub = dump_difference("0", mine, theirs)
        assert set(pub.key_vals) == {"k"}
        assert not pub.to_be_updated_keys

    def test_theirs_better(self):
        mine = {"k": v(version=2)}
        theirs = {"k": v(version=3)}
        pub = dump_difference("0", mine, theirs)
        assert not pub.key_vals
        assert pub.to_be_updated_keys == ["k"]

    def test_equal_omitted(self):
        mine = {"k": v()}
        pub = dump_difference("0", mine, {"k": v()})
        assert not pub.key_vals and not pub.to_be_updated_keys


class TestTtlCountdown:
    def test_expire_matching_entry(self):
        q = TtlCountdownQueue()
        kv = {"k": v(ttl=1000)}
        q.track("k", kv["k"], now=100.0)
        assert q.expire(kv, now=100.5) == []
        assert q.expire(kv, now=101.1) == ["k"]
        assert "k" not in kv

    def test_refresh_strands_stale_entry(self):
        q = TtlCountdownQueue()
        kv = {"k": v(ttl=1000, ttl_version=0)}
        q.track("k", kv["k"], now=100.0)
        kv["k"].ttl_version = 1  # refreshed
        q.track("k", kv["k"], now=100.9)
        assert q.expire(kv, now=101.1) == []  # stale entry ignored
        assert q.expire(kv, now=102.0) == ["k"]

    def test_infinite_ttl_not_tracked(self):
        q = TtlCountdownQueue()
        q.track("k", v(ttl=-1))
        assert len(q) == 0
        assert q.next_expiry_in_s() is None


# ---------------------------------------------------------------------------
# multi-instance sync / flooding over real TCP
# ---------------------------------------------------------------------------

async def _start_stores(n, config=None):
    wrappers = [KvStoreWrapper(f"store{i}", config=config) for i in range(n)]
    for w in wrappers:
        await w.start()
    return wrappers


async def _stop_stores(wrappers):
    for w in wrappers:
        await w.stop()


class TestKvStoreSync:
    @run_async
    async def test_two_store_full_sync(self):
        a, b = await _start_stores(2)
        try:
            a.set_key("k1", b"v1")
            b.set_key("k2", b"v2")
            a.add_peer(b)
            b.add_peer(a)
            await wait_converged([a, b])
            assert a.get_key("k2").value == b"v2"
            assert b.get_key("k1").value == b"v1"
            assert a.peer_state("store1") == KvStorePeerState.INITIALIZED
            assert b.peer_state("store0") == KvStorePeerState.INITIALIZED
        finally:
            await _stop_stores([a, b])

    @run_async
    async def test_full_sync_conflict_resolution(self):
        """Same key both sides: higher version wins on both after sync."""
        a, b = await _start_stores(2)
        try:
            a.set_key("k", b"old", version=1)
            b.set_key("k", b"new", version=2)
            a.add_peer(b)
            b.add_peer(a)
            await wait_converged([a, b])
            assert a.get_key("k").value == b"new"
            assert a.get_key("k").version == 2
        finally:
            await _stop_stores([a, b])

    @run_async
    async def test_three_store_line_convergence(self):
        """a - b - c line: writes at the ends reach the other end through
        the middle store's flooding."""
        stores = await _start_stores(3)
        a, b, c = stores
        try:
            a.add_peer(b)
            b.add_peer(a)
            b.add_peer(c)
            c.add_peer(b)
            await wait_until(
                lambda: a.peer_state("store1") == KvStorePeerState.INITIALIZED
                and c.peer_state("store1") == KvStorePeerState.INITIALIZED
            )
            a.set_key("from-a", b"1")
            c.set_key("from-c", b"2")
            await wait_converged(stores)
            assert c.get_key("from-a").value == b"1"
            assert a.get_key("from-c").value == b"2"
        finally:
            await _stop_stores(stores)

    @run_async
    async def test_flood_loop_suppression_full_mesh(self):
        """Full mesh of 3: node_ids path vector prevents a publication from
        revisiting stores (no infinite re-flood; counters stay bounded)."""
        stores = await _start_stores(3)
        a, b, c = stores
        try:
            for x in stores:
                for y in stores:
                    if x is not y:
                        x.add_peer(y)
            await wait_until(
                lambda: all(
                    w.peer_state(o.node_name) == KvStorePeerState.INITIALIZED
                    for w in stores
                    for o in stores
                    if o is not w
                )
            )
            a.set_key("k", b"v")
            await wait_converged(stores)
            # settle: any residual (suppressed) floods drain
            await asyncio.sleep(0.2)
            assert all(w.get_key("k").value == b"v" for w in stores)
        finally:
            await _stop_stores(stores)

    @run_async
    async def test_publication_emitted_locally(self):
        a, b = await _start_stores(2)
        try:
            a.add_peer(b)
            b.add_peer(a)
            b.set_key("k", b"v")
            # a's updates queue must see the flooded key
            async def find_key():
                while True:
                    pub = await a.updates_reader.get()
                    if isinstance(pub, Publication) and "k" in pub.key_vals:
                        return pub
            pub = await asyncio.wait_for(find_key(), timeout=5)
            assert pub.key_vals["k"].value == b"v"
        finally:
            await _stop_stores([a, b])

    @run_async
    async def test_peer_down_backoff_and_recovery(self):
        """Peer unreachable -> IDLE with backoff; once reachable, syncs."""
        a = KvStoreWrapper("store0")
        await a.start()
        b = KvStoreWrapper("store1")
        try:
            # b not started: connection refused
            from openr_tpu.types import AreaPeerEvent, PeerSpec

            await b.start()
            port = b.port
            await b.store.server.stop()  # listening socket gone
            a.peer_updates_queue.push(
                {
                    "0": AreaPeerEvent(
                        peers_to_add={
                            "store1": PeerSpec(
                                peer_addr="127.0.0.1", ctrl_port=port
                            )
                        }
                    )
                }
            )
            await asyncio.sleep(0.3)
            assert a.peer_state("store1") in (
                KvStorePeerState.IDLE,
                KvStorePeerState.SYNCING,
            )
            # bring b up on the same port; a's backoff retry should succeed
            await b.store.server.start(port=port)
            await wait_until(
                lambda: a.peer_state("store1")
                == KvStorePeerState.INITIALIZED,
                timeout_s=10,
            )
        finally:
            await a.stop()
            await b.stop()

    @run_async
    async def test_flood_failure_resets_peer_then_recovers(self):
        """Injected flood fault (the `kvstore.flood` chaos site): the
        transport-failure path must reset the peer session, and the
        backoff re-sync must carry the dropped key across anyway."""
        from openr_tpu.runtime.faults import registry

        a, b = await _start_stores(2)
        try:
            a.add_peer(b)
            b.add_peer(a)
            await wait_until(
                lambda: a.peer_state("store1")
                == KvStorePeerState.INITIALIZED
            )
            registry.arm("kvstore.flood", one_shot=True)
            a.set_key("k-fault", b"v")
            # the failed flood dropped the update, but full sync on the
            # re-established session converges the key anyway
            await wait_until(
                lambda: b.get_key("k-fault") is not None, timeout_s=10
            )
            assert b.get_key("k-fault").value == b"v"
            await wait_until(
                lambda: a.peer_state("store1")
                == KvStorePeerState.INITIALIZED,
                timeout_s=10,
            )
            # one_shot: the schedule disarmed itself after firing
            assert registry.list()["armed"] == []
        finally:
            registry.clear()
            await _stop_stores([a, b])

    @run_async
    async def test_del_peer_stops_flooding(self):
        a, b = await _start_stores(2)
        try:
            a.add_peer(b)
            b.add_peer(a)
            await wait_until(
                lambda: a.peer_state("store1") == KvStorePeerState.INITIALIZED
            )
            a.del_peer("store1")
            await wait_until(lambda: a.peer_state("store1") is None)
            a.set_key("after-del", b"x")
            await asyncio.sleep(0.3)
            assert b.get_key("after-del") is None
        finally:
            await _stop_stores([a, b])


class TestKvStoreTtl:
    @run_async
    async def test_key_expires(self):
        (a,) = await _start_stores(1)
        try:
            a.set_key("mortal", b"v", ttl_ms=80)
            assert a.get_key("mortal") is not None
            await wait_until(lambda: a.get_key("mortal") is None, timeout_s=3)
            # expiry publication observed locally
            pub = await asyncio.wait_for(a.updates_reader.get(), timeout=2)
            while "mortal" not in pub.expired_keys:
                pub = await asyncio.wait_for(a.updates_reader.get(), timeout=2)
        finally:
            await _stop_stores([a])

    @run_async
    async def test_ttl_decrement_on_flood(self):
        a, b = await _start_stores(2)
        try:
            a.add_peer(b)
            b.add_peer(a)
            a.set_key("k", b"v", ttl_ms=10_000)
            await wait_until(lambda: b.get_key("k") is not None)
            assert b.get_key("k").ttl_ms < 10_000  # decayed in transit
        finally:
            await _stop_stores([a, b])


class TestSelfOriginated:
    @run_async
    async def test_persist_and_flood(self):
        a, b = await _start_stores(2)
        try:
            a.add_peer(b)
            b.add_peer(a)
            a.persist_key("adj:store0", b"adjdb")
            await wait_until(lambda: b.get_key("adj:store0") is not None)
            assert b.get_key("adj:store0").originator_id == "store0"
        finally:
            await _stop_stores([a, b])

    @run_async
    async def test_version_bump_to_win(self):
        """A persisted key beaten by a remote value gets re-advertised with
        a higher version (ref self-originated key override protection)."""
        (a,) = await _start_stores(1)
        try:
            a.persist_key("k", b"mine")
            await wait_until(lambda: a.get_key("k") is not None)
            v1 = a.get_key("k").version
            # a rogue higher-version value arrives
            a.store._merge_and_flood(
                Publication(
                    key_vals={
                        "k": Value(
                            version=v1 + 5,
                            originator_id="zzz-rogue",
                            value=b"theirs",
                        )
                    },
                    area="0",
                )
            )
            await wait_until(
                lambda: a.get_key("k").originator_id == "store0"
                and a.get_key("k").version > v1 + 5
            )
            assert a.get_key("k").value == b"mine"
        finally:
            await _stop_stores([a])

    @run_async
    async def test_ttl_refresh_keeps_key_alive(self):
        from openr_tpu.config import KvstoreConfig

        cfg = KvstoreConfig(key_ttl_ms=300)
        (a,) = await _start_stores(1, config=cfg)
        try:
            a.persist_key("k", b"v")  # ttl 300ms, refresh every ~75ms
            await asyncio.sleep(1.0)
            live = a.get_key("k")
            assert live is not None  # refreshed past several lifetimes
            assert live.ttl_version > 0
        finally:
            await _stop_stores([a])

    @run_async
    async def test_initial_sync_event(self):
        from openr_tpu.types import InitializationEvent

        a, b = await _start_stores(2)
        try:
            a.add_peer(b)
            b.add_peer(a)

            async def find_event():
                while True:
                    item = await a.updates_reader.get()
                    if item == InitializationEvent.KVSTORE_SYNCED:
                        return item

            assert (
                await asyncio.wait_for(find_event(), timeout=5)
            ) == InitializationEvent.KVSTORE_SYNCED
        finally:
            await _stop_stores([a, b])


class TestImminentTtlAlarm:
    """ref KvStore.h:553-564 — warn when an owned finite-ttl adj key
    nears expiry without a refresh."""

    @run_async
    async def test_unrefreshed_adj_key_raises_alarm(self):
        import time as _time

        from openr_tpu.runtime.counters import counters

        (a,) = await _start_stores(1)
        try:
            a.persist_key("adj:store0", b"adjdb", ttl_ms=10_000)
            a.persist_key("prefix:store0", b"p", ttl_ms=10_000)
            await wait_until(
                lambda: a.get_key("adj:store0") is not None
                and a.get_key("prefix:store0") is not None
            )
            st = a.store
            # fresh: no alarm
            assert st._check_imminent_ttls() == 0
            # simulate a wedged refresh pipeline: pretend the last
            # advertisement happened 9s ago on a 10s ttl (> 3/4)
            for area in st.areas.values():
                for own in area.self_originated.values():
                    own.last_refresh = _time.monotonic() - 9.0
            before = counters.get_counters().get(
                "kvstore.store0.imminent_ttl_expiry", 0
            )
            # only the adj: key alarms, not prefix:
            assert st._check_imminent_ttls() == 1
            after = counters.get_counters()["kvstore.store0.imminent_ttl_expiry"]
            assert after == before + 1
        finally:
            await _stop_stores([a])

    @run_async
    async def test_healthy_refresh_keeps_alarm_quiet(self):
        from openr_tpu.config import KvstoreConfig

        cfg = KvstoreConfig(key_ttl_ms=300)
        (a,) = await _start_stores(1, config=cfg)
        try:
            a.persist_key("adj:store0", b"adjdb")  # refreshed every ~75ms
            await asyncio.sleep(0.6)  # two ttl lifetimes of refreshes
            assert a.store._check_imminent_ttls() == 0
        finally:
            await _stop_stores([a])
